"""Alternating least squares on JAX — implicit (Hu/Koren/Volinsky, the
paper cited at reference ALSUpdate.java:60-68) and explicit variants.

Reference behavior being matched: app/oryx-app-mllib/.../als/ALSUpdate.java
:141-152 delegates to Spark MLlib ALS (rank/iterations/lambda/alpha,
implicit flag); this module is the TPU-native replacement for that
distributed factorizer.  Same objective as MLlib:

  implicit:  min Σ_ui c_ui (p_ui - x_u·y_i)^2 + λ Σ_u n_u|x_u|^2 + ...
             c = 1 + α|r|,  p = 1 if r > 0 else 0
  explicit:  min Σ_observed (r_ui - x_u·y_i)^2 + λ n_u |x_u|^2 + ...
  (ALS-WR λ scaling by per-row rating count, as MLlib does)

TPU-native design (NOT a translation of MLlib's block solver):
 - interactions live as COO on host, grouped into CSR by the side being
   solved; users are sorted by degree and packed into degree-bucketed
   batches padded to power-of-2 widths, so XLA sees a handful of static
   shapes and every solve is a large batched MXU matmul;
 - one jitted kernel builds all B normal-equation systems of a batch at
   once:  A_u = [G +] Yg_u^T diag(w_u) Yg_u + λ n_u I,  b_u = Yg_u^T t_u
   with Yg the (B,P,k) gathered factor rows, then a batched
   jnp.linalg.solve — there is no per-user host loop anywhere;
 - the Gramian G = Y^T Y (implicit-only base term) is one matmul per
   half-sweep.

The same kernel solves the item side by swapping roles.
"""

from __future__ import annotations

import logging
import math
from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...common.rand import RandomManager
from ...ml.integrity import NumericalDivergenceError
from ...resilience.faults import fire as _fault
from .common import ParsedRatings

_log = logging.getLogger(__name__)

__all__ = ["train_als", "rescue_retrain_f64", "ALSModel", "predict_pairs",
           "score_all_items"]

# max padded interaction slots (B*P) per solve batch; bounds peak memory
# of the (B, P, k) gather at ~slots*k*4 bytes
_BATCH_SLOT_BUDGET = 1 << 19
_MAX_B = 4096

# floor for the escalated-regularization rescue rung: an effectively
# unregularized candidate (lambda ~ 0) whose f64 systems are still
# singular gets at least this much
_RESCUE_MIN_LAMBDA = 1e-3


class ALSModel(NamedTuple):
    user_ids: list[str]
    item_ids: list[str]
    X: np.ndarray  # (n_users, k) float32
    Y: np.ndarray  # (n_items, k) float32
    # non-None when the f32 factorization diverged and a rescue rung
    # produced these factors instead: {"precision", "trigger_iteration",
    # "escalated_lambda"} — carried into the candidate's PMML so the
    # generation records HOW it trained, not just that it did
    rescue: dict | None = None


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _csr_by(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, n_rows: int):
    """Group COO by row: returns (order-sorted cols, vals, row_ptr)."""
    order = np.argsort(rows, kind="stable")
    sorted_rows = rows[order]
    counts = np.bincount(sorted_rows, minlength=n_rows)
    row_ptr = np.concatenate([[0], np.cumsum(counts)])
    return cols[order], vals[order], row_ptr, counts


def _plan_batches(counts: np.ndarray) -> list[np.ndarray]:
    """Pack row indices into degree-bucketed batches.

    Rows are sorted by degree descending; each batch's padded width P is
    its max degree rounded to a power of two, and batch size B is capped
    so B*P stays within the slot budget.  Every batch is emitted at
    EXACTLY its width's full B — the tail of a degree class pads with
    dummy row index len(counts) (scattered to a sacrificial extra row) —
    so each P value compiles the solve kernel once; arbitrary tail sizes
    would compile a fresh executable per tail.  Returns (row indices,
    padded width P) pairs; the indices may contain the dummy index.
    """
    n = len(counts)
    order = np.argsort(-counts, kind="stable")
    batches = []
    i = 0
    while i < n:
        p = _next_pow2(max(1, int(counts[order[i]])))
        b = max(1, min(_MAX_B, _BATCH_SLOT_BUDGET // p))
        batch = order[i:i + b]
        if len(batch) < b:
            batch = np.concatenate(
                [batch, np.full(b - len(batch), n, dtype=batch.dtype)])
        batches.append((batch, p))
        i += b
    return batches


@partial(jax.jit, static_argnames=("implicit",))
def _solve_batch(Yg, vals, mask, G, lam, alpha, implicit: bool):
    """Solve the batch's normal equations.

    Yg:   (B, P, k) gathered opposite-side factor rows (zeros at padding)
    vals: (B, P)    interaction strengths (zeros at padding)
    mask: (B, P)    1.0 at real interactions
    G:    (k, k)    Y^T Y, the implicit base term (ignored if explicit)
    """
    k = Yg.shape[-1]
    n_u = jnp.sum(mask, axis=1)  # per-row interaction count (ALS-WR reg)
    if implicit:
        w = alpha * jnp.abs(vals) * mask          # c - 1
        t = (1.0 + w) * (vals > 0.0)              # c * p
    else:
        w = mask
        t = vals * mask
    # A_u = [G +] Yg^T diag(w) Yg + lam * n_u * I   — one batched matmul
    Yw = Yg * w[:, :, None]
    A = jnp.einsum("bpk,bpl->bkl", Yw, Yg,
                   preferred_element_type=jnp.float32)
    if implicit:
        A = A + G[None, :, :]
    # rows with no interactions would make A singular in explicit mode
    # (A = 0); regularize them with a unit count and zero the solution —
    # MLlib simply has no such row, so a zero factor is the equivalent
    A = A + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * \
        jnp.eye(k, dtype=A.dtype)[None]
    b = jnp.einsum("bpk,bp->bk", Yg, t, preferred_element_type=jnp.float32)
    x = jnp.linalg.solve(A, b[..., None])[..., 0]
    return jnp.where((n_u > 0)[:, None], x, 0.0)


@jax.jit
def _gramian(Y):
    return jnp.matmul(Y.T, Y, preferred_element_type=jnp.float32)


class _SidePlan(NamedTuple):
    """Device-resident packed batches for one half-sweep.

    The sparsity pattern is fixed for the whole factorization, so the
    degree-bucketed packing (and its device upload) happens ONCE and is
    reused by every iteration — the per-iteration work is pure compute.
    """

    n_rows: int
    # per batch: (device row indices (B,), device cols (B,P),
    #             vals (B,P), mask (B,P))
    batches: list[tuple[jax.Array, jax.Array, jax.Array, jax.Array]]


def _pack_side(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
               n_rows: int) -> _SidePlan:
    """CSR-group by row, then pack into padded batches with vectorized
    scatter (no per-row Python loop).  Dummy row indices (== n_rows,
    from tail padding) carry zero interactions and scatter to the
    sacrificial extra row of the output."""
    s_cols, s_vals, row_ptr, counts = _csr_by(rows, cols, vals, n_rows)
    counts_ext = np.concatenate([counts, [0]])     # dummy row: degree 0
    row_ptr_ext = np.concatenate([row_ptr, [row_ptr[-1]]])
    batches = []
    for batch_rows, p in _plan_batches(counts):
        bsz = len(batch_rows)
        c = counts_ext[batch_rows].astype(np.int64)
        total = int(c.sum())
        # flat source/destination index vectors for all real slots at once
        within = np.arange(total, dtype=np.int64) - np.repeat(
            np.cumsum(c) - c, c)
        src = np.repeat(row_ptr_ext[batch_rows], c) + within
        dst = np.repeat(np.arange(bsz, dtype=np.int64) * p, c) + within
        bcols = np.zeros(bsz * p, dtype=np.int32)
        bvals = np.zeros(bsz * p, dtype=np.float32)
        bmask = np.zeros(bsz * p, dtype=np.float32)
        bcols[dst] = s_cols[src]
        bvals[dst] = s_vals[src]
        bmask[dst] = 1.0
        batches.append((jnp.asarray(batch_rows.astype(np.int32)),
                        jnp.asarray(bcols.reshape(bsz, p)),
                        jnp.asarray(bvals.reshape(bsz, p)),
                        jnp.asarray(bmask.reshape(bsz, p))))
    return _SidePlan(n_rows, batches)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(out, rows, x):
    # donating `out` lets XLA scatter in place instead of copying the
    # full factor matrix every batch
    return out.at[rows].set(x)


def _solve_side(opposite: jax.Array, plan: _SidePlan,
                k: int, lam: float, alpha: float,
                implicit: bool) -> jax.Array:
    """One half-sweep: solve every row's factor given the opposite side.

    Everything stays on device — batches async-dispatch back to back,
    and the returned factor feeds the next half-sweep's gathers directly
    (factors cross the PCIe/tunnel boundary only when the caller
    materializes them).  A backstop window bounds how many (B, P, k)
    gather buffers can be live at once without any device->host
    transfer: block_until_ready on an old batch is a sync, not a copy.
    The bound is slot-based and GENEROUS (~32 × slot-budget × k × 4B ≈
    6.7 GB at k=100) because each sync costs a full host<->device round
    trip and measurably serializes the dispatch pipeline (a window of 8
    cost more wall-clock at ML20M scale than it saved in memory) — it
    exists to stop a pathological many-hundred-batch side from pinning
    unbounded HBM, not to engage at normal scales."""
    G = _gramian(opposite) if implicit else jnp.zeros((k, k), jnp.float32)
    lam32, alpha32 = jnp.float32(lam), jnp.float32(alpha)
    # one sacrificial extra row absorbs the scatters of dummy (tail
    # padding) batch indices; sliced off on return
    out = jnp.zeros((plan.n_rows + 1, k), dtype=jnp.float32)
    pending: list[tuple[int, jax.Array]] = []
    pending_slots = 0
    for batch_rows, bcols, bvals, bmask in plan.batches:
        Yg = opposite[bcols]
        x = _solve_batch(Yg, bvals, bmask, G, lam32, alpha32, implicit)
        out = _scatter_rows(out, batch_rows, x)
        slots = int(bcols.shape[0] * bcols.shape[1])
        pending.append((slots, x))
        pending_slots += slots
        while pending_slots > 32 * _BATCH_SLOT_BUDGET:
            done_slots, done_x = pending.pop(0)
            done_x.block_until_ready()
            pending_slots -= done_slots
    return out[:plan.n_rows]


def _solve_side_f64_host(opposite: np.ndarray, plan: _SidePlan,
                         k: int, lam: float, alpha: float,
                         implicit: bool) -> np.ndarray:
    """Host float64 half-sweep over the SAME packed batches as the
    device kernel — identical masking, ALS-WR scaling, and empty-row
    semantics, only the arithmetic precision differs.  This is the
    rescue precision: MLlib factors in f64 (ALSUpdate.java:88-152), so
    a candidate whose f32 normal equations degenerate gets retried
    here rather than reported as untrainable."""
    G = opposite.T @ opposite if implicit else None
    # same sacrificial extra row absorbing dummy (tail padding) indices
    out = np.zeros((plan.n_rows + 1, k), dtype=np.float64)
    eye = np.eye(k, dtype=np.float64)
    for batch_rows, bcols, bvals, bmask in plan.batches:
        rows = np.asarray(batch_rows)
        Yg = opposite[np.asarray(bcols)]            # (B, P, k) float64
        vals = np.asarray(bvals, dtype=np.float64)
        mask = np.asarray(bmask, dtype=np.float64)
        n_u = mask.sum(axis=1)
        if implicit:
            w = alpha * np.abs(vals) * mask
            t = (1.0 + w) * (vals > 0.0)
        else:
            w = mask
            t = vals * mask
        A = np.einsum("bpk,bpl->bkl", Yg * w[:, :, None], Yg)
        if implicit:
            A = A + G[None, :, :]
        A += (lam * np.maximum(n_u, 1.0))[:, None, None] * eye[None]
        b = np.einsum("bpk,bp->bk", Yg, t)
        x = np.linalg.solve(A, b[..., None])[..., 0]
        x[n_u == 0] = 0.0
        out[rows] = x
    return out[:plan.n_rows]


def _train_f64_host(user_plan: _SidePlan, item_plan: _SidePlan,
                    n_users: int, n_items: int, k: int, lam: float,
                    alpha: float, implicit: bool, iterations: int,
                    seed_val: int) -> tuple[np.ndarray, np.ndarray] | None:
    """Full float64 host retrain from the same seed/init; returns
    (X, Y) as float32, or None when even f64 diverges or hits an
    exactly singular system."""
    rng = np.random.default_rng(seed_val)
    Y = rng.standard_normal((n_items, k)) / math.sqrt(k)
    try:
        for _ in range(iterations):
            X = _solve_side_f64_host(Y, user_plan, k, lam, alpha, implicit)
            Y = _solve_side_f64_host(X, item_plan, k, lam, alpha, implicit)
    except np.linalg.LinAlgError:
        return None
    if not (np.all(np.isfinite(X)) and np.all(np.isfinite(Y))):
        return None
    return X.astype(np.float32), Y.astype(np.float32)


def _factors_finite(X: jax.Array, Y: jax.Array) -> bool:
    # NaN-propagating sums: two scalars cross the transport, not the
    # factor matrices
    return bool(jnp.isfinite(jnp.sum(X)) & jnp.isfinite(jnp.sum(Y)))


def _f64_ladder(user_plan: _SidePlan, item_plan: _SidePlan,
                n_users: int, n_items: int, k: int, lam: float,
                alpha: float, implicit: bool, iterations: int,
                seed_val: int, trigger_iteration: int | None
                ) -> tuple[np.ndarray, np.ndarray, dict]:
    """The f64 -> escalated-lambda rungs shared by train_als and the
    distributed trainer's rescue; returns (X, Y, rescue annotation) or
    raises NumericalDivergenceError when both rungs fail."""
    rescue = {"precision": "float64", "trigger_iteration": trigger_iteration,
              "escalated_lambda": None}
    factors = _train_f64_host(user_plan, item_plan, n_users, n_items, k,
                              lam, alpha, implicit, iterations, seed_val)
    if factors is None:
        lam_esc = max(lam * 10.0, _RESCUE_MIN_LAMBDA)
        _log.warning("float64 retrain also diverged; escalating "
                     "regularization lambda %g -> %g", lam, lam_esc)
        rescue["escalated_lambda"] = lam_esc
        factors = _train_f64_host(user_plan, item_plan, n_users, n_items,
                                  k, lam_esc, alpha, implicit, iterations,
                                  seed_val)
        if factors is None:
            raise NumericalDivergenceError(
                f"ALS diverged at every rescue rung (features={k} "
                f"lambda={lam}, escalated {lam_esc})")
    X_r, Y_r = factors
    _log.info("ALS float64 rescue succeeded (%s)", rescue)
    return X_r, Y_r, rescue


def rescue_retrain_f64(ratings: ParsedRatings, features: int, lam: float,
                       alpha: float, implicit: bool, iterations: int,
                       seed: int | None = None) -> ALSModel:
    """Standalone f64 rescue for factorization paths without an in-loop
    ladder (the distributed trainer): repack the interactions and run
    the f64 -> escalated-lambda rungs directly.  Returns a
    rescue-annotated ALSModel or raises NumericalDivergenceError."""
    n_users = len(ratings.user_ids)
    n_items = len(ratings.item_ids)
    user_plan = _pack_side(ratings.users, ratings.items, ratings.values,
                           n_users)
    item_plan = _pack_side(ratings.items, ratings.users, ratings.values,
                           n_items)
    seed_val = RandomManager.random_seed() if seed is None else seed
    X_r, Y_r, rescue = _f64_ladder(user_plan, item_plan, n_users, n_items,
                                   features, lam, alpha, implicit,
                                   iterations, seed_val,
                                   trigger_iteration=None)
    return ALSModel(ratings.user_ids, ratings.item_ids, X_r, Y_r,
                    rescue=rescue)


def train_als(ratings: ParsedRatings,
              features: int,
              lam: float,
              alpha: float,
              implicit: bool,
              iterations: int,
              seed: int | None = None,
              on_iteration: Callable[[int, np.ndarray, np.ndarray], None]
              | None = None) -> ALSModel:
    """Factor the interaction matrix into X (users) and Y (items).

    `on_iteration(i, X, Y)` fires after each full sweep — used by the
    bench harness for per-epoch timing/convergence traces.

    Numerical rescue ladder: the f32 device factorization is checked
    for divergence after every sweep; on NaN/Inf the candidate retrains
    in float64 on host (same seed and init), and if even f64 cannot
    train it, once more with escalated regularization.  The returned
    model's ``rescue`` field records the rung taken; only a candidate
    that exhausts the ladder raises NumericalDivergenceError.  This
    keeps the usable hyperparameter region as wide as the reference's
    f64 MLlib trainer instead of silently narrower.
    """
    n_users = len(ratings.user_ids)
    n_items = len(ratings.item_ids)
    k = features
    if n_users == 0 or n_items == 0:
        return ALSModel(ratings.user_ids, ratings.item_ids,
                        np.zeros((0, k), np.float32), np.zeros((0, k), np.float32))

    user_plan = _pack_side(ratings.users, ratings.items, ratings.values,
                           n_users)
    item_plan = _pack_side(ratings.items, ratings.users, ratings.values,
                           n_items)

    seed_val = RandomManager.random_seed() if seed is None else seed
    rng = np.random.default_rng(seed_val)
    # small random init, scaled like MLlib's (normalized gaussian / sqrt(k))
    Y = jnp.asarray(
        (rng.standard_normal((n_items, k)) / math.sqrt(k)).astype(np.float32))
    X = jnp.zeros((n_users, k), dtype=jnp.float32)

    diverged_at = None
    for it in range(iterations):
        # factors never leave the device between half-sweeps
        X = _solve_side(Y, user_plan, k, lam, alpha, implicit)
        Y = _solve_side(X, item_plan, k, lam, alpha, implicit)
        # chaos seam: poison this sweep's factors so tests drive the
        # rescue ladder deterministically on healthy data
        if _fault("trainer-f32-poison") == "drop":
            X = X.at[0, 0].set(jnp.nan)
        # one transport round trip per sweep — deliberate: divergence
        # typically appears within the first couple of sweeps, and
        # breaking early saves whole sweeps of NaN compute (and pins
        # trigger_iteration), worth far more than the RTT the
        # INFO-logging sync below was already paying in practice
        if not _factors_finite(X, Y):
            diverged_at = it
            break
        _log.info("ALS iteration %d/%d done", it + 1, iterations)
        if on_iteration is not None:
            on_iteration(it, np.asarray(X), np.asarray(Y))

    if diverged_at is None:
        return ALSModel(ratings.user_ids, ratings.item_ids,
                        np.asarray(X), np.asarray(Y))

    _log.warning("ALS f32 factorization diverged at iteration %d/%d "
                 "(features=%d lambda=%g); rescuing in float64",
                 diverged_at + 1, iterations, k, lam)
    X_r, Y_r, rescue = _f64_ladder(user_plan, item_plan, n_users, n_items,
                                   k, lam, alpha, implicit, iterations,
                                   seed_val, trigger_iteration=diverged_at)
    return ALSModel(ratings.user_ids, ratings.item_ids, X_r, Y_r,
                    rescue=rescue)


@jax.jit
def _predict_pairs_kernel(X, Y, users, items):
    return jnp.einsum("nk,nk->n", X[users], Y[items])


def predict_pairs(model_x: np.ndarray, model_y: np.ndarray,
                  users: np.ndarray, items: np.ndarray) -> np.ndarray:
    """Predicted strengths for (user, item) index pairs — one gather+dot."""
    return np.asarray(_predict_pairs_kernel(
        jnp.asarray(model_x), jnp.asarray(model_y),
        jnp.asarray(users), jnp.asarray(items)))


@jax.jit
def score_all_items(x_u, Y):
    """All-items scores for one or more users: the serving-side matmul."""
    return jnp.matmul(x_u, Y.T, preferred_element_type=jnp.float32)
