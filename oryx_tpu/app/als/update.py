"""The ALS batch app: MLUpdate implementation over the JAX trainer.

Reference: app/oryx-app-mllib/src/main/java/com/cloudera/oryx/app/batch/
mllib/als/ALSUpdate.java — hyperparams from config :84-101, buildModel
:109-180 (parse -> ID-index maps -> decay -> aggregate -> factorize ->
PMML), evaluate :200-247 (implicit mean AUC / explicit -RMSE),
publishAdditionalModelData :287-319 (stream Y then X as "UP"-style JSON,
user rows joined with known-items), mfModelToPMML :430-473 (X/Y as
gzipped JSON text files + XIDs/YIDs extensions), time-based
splitNewDataToTrainTest :326-343.
"""

from __future__ import annotations

import gzip
import io
import json
import logging
from typing import Sequence
from xml.etree.ElementTree import Element

import numpy as np

from ...common import pmml as pmml_io
from ...common import store
from ...common import text as text_utils
from ...common.config import Config
from ...kafka.api import KEY_UP, KeyMessage, TopicProducer
from ...ml import params as hp
from ...ml.integrity import NumericalDivergenceError, is_finite_array
from ...ml.mlupdate import MLUpdate
from . import common as als_common
from . import evaluation
from . import slices
from .trainer import ALSModel, train_als

_log = logging.getLogger(__name__)

__all__ = ["ALSUpdate", "save_features", "load_features"]


def save_features(path: str, ids: Sequence[str], matrix: np.ndarray) -> None:
    """Write a factor matrix as gzipped JSON lines ``["id",[floats]]`` —
    the artifact format serving/speed layers read back, on any store
    scheme (reference: ALSUpdate.saveFeaturesRDD :490-499 writes to the
    shared filesystem)."""
    path = store.mkdirs(path)
    with store.open_write(store.join(path, "part-00000.gz")) as raw, \
            gzip.open(raw, "wt", encoding="utf-8") as f:
        for id_, row in zip(ids, matrix):
            f.write(text_utils.join_json([id_, [round(float(v), 8) for v in row]]))
            f.write("\n")


def load_features(path: str) -> tuple[list[str], np.ndarray]:
    """Read a factor matrix directory written by save_features
    (reference: ALSUpdate.readFeaturesRDD :533-541)."""
    ids: list[str] = []
    rows: list[list[float]] = []
    for part in store.glob(path, "part-*"):
        with store.open_read(part) as raw:
            opener = gzip.open(raw, "rt", encoding="utf-8") \
                if part.endswith(".gz") \
                else io.TextIOWrapper(raw, encoding="utf-8")
            with opener as f:
                for line in f:
                    if line.strip():
                        id_, vector = json.loads(line)
                        ids.append(str(id_))
                        rows.append(vector)
    matrix = np.asarray(rows, dtype=np.float32) if rows else \
        np.zeros((0, 0), dtype=np.float32)
    return ids, matrix


class ALSUpdate(MLUpdate):
    """Batch ALS: factor the full interaction history each generation."""

    def __init__(self, config: Config):
        super().__init__(config)
        self.iterations = config.get_int("oryx.als.iterations")
        self.implicit = config.get_bool("oryx.als.implicit")
        self.log_strength = config.get_bool("oryx.als.logStrength")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.decay_factor = config.get_double("oryx.als.decay.factor")
        self.decay_zero_threshold = config.get_double("oryx.als.decay.zero-threshold")
        # sharded model distribution (slices.py): murmur2 ring size for
        # the per-slice artifacts a too-large-to-inline model publishes
        # alongside its MODEL-REF; 0 disables (pure reference behavior)
        self.publish_slices = config.get_int("oryx.als.publish.slices")
        # IVF ANN index publish (ivf.py): train the coarse quantizer at
        # publish time and ship centroids + per-slice cell assignments
        # with the sliced artifacts, so a serving replica's index build
        # skips the k-means training entirely (oryx.als.ann.*)
        self.publish_ann_index = config.get_bool(
            "oryx.als.ann.publish-index")
        from .ivf import AnnConfig
        self.ann_config = AnnConfig.from_config(config)
        if self.iterations <= 0:
            raise ValueError("iterations must be positive")
        if not 0.0 < self.decay_factor <= 1.0:
            raise ValueError("decay factor must be in (0,1]")
        if self.decay_zero_threshold < 0.0:
            raise ValueError("decay zero threshold must be >= 0")
        from ...parallel.mesh import mesh_from_config
        self.mesh = mesh_from_config(config)
        self._hyper_params = [
            hp.from_config(config, "oryx.als.hyperparams.features"),
            hp.from_config(config, "oryx.als.hyperparams.lambda"),
            hp.from_config(config, "oryx.als.hyperparams.alpha"),
        ]
        if self.log_strength:
            self._hyper_params.append(
                hp.from_config(config, "oryx.als.hyperparams.epsilon"))

    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        return list(self._hyper_params)

    # -- train --------------------------------------------------------------

    def build_model(self, train_data, hyper_parameters, candidate_path) -> Element:
        features = int(hyper_parameters[0])
        lam = float(hyper_parameters[1])
        alpha = float(hyper_parameters[2])
        epsilon = float(hyper_parameters[3]) if self.log_strength else float("nan")
        if features <= 0 or lam < 0.0 or alpha <= 0.0:
            raise ValueError("bad hyperparameters")
        events = als_common.parse_events(train_data, self.decay_factor,
                                         self.decay_zero_threshold)
        ratings = als_common.aggregate(events, self.implicit,
                                       self.log_strength, epsilon)
        try:
            if self.mesh is not None:
                from ...parallel.als_dist import train_als_distributed
                model = train_als_distributed(ratings, features, lam, alpha,
                                              self.implicit, self.iterations,
                                              self.mesh)
                if not (is_finite_array(model.X)
                        and is_finite_array(model.Y)):
                    # the distributed trainer has no in-loop ladder;
                    # give its diverged candidates the same f64 rescue
                    # the single-device path gets
                    _log.warning("Distributed ALS diverged "
                                 "(features=%d lambda=%g); rescuing in "
                                 "float64 on host", features, lam)
                    from .trainer import rescue_retrain_f64
                    model = rescue_retrain_f64(ratings, features, lam,
                                               alpha, self.implicit,
                                               self.iterations)
            else:
                model = train_als(ratings, features, lam, alpha, self.implicit,
                                  self.iterations)
        except NumericalDivergenceError:
            # every rescue rung failed: a clean per-candidate failure —
            # the search skips it; one bad combo must not kill the sweep
            _log.exception("Candidate (features=%d lambda=%g) diverged "
                           "beyond rescue; skipping", features, lam)
            return None
        # cheap in-memory gate BEFORE the artifacts are written: the
        # rescue ladder should make this unreachable, and catching a
        # regression here costs one array pass instead of a round trip
        # through the gzipped artifacts
        if not (is_finite_array(model.X) and is_finite_array(model.Y)):
            _log.warning("Candidate (features=%d lambda=%g) produced "
                         "non-finite factors; skipping", features, lam)
            return None
        return self._model_to_pmml(model, features, lam, alpha, epsilon,
                                   candidate_path)

    def _model_to_pmml(self, model: ALSModel, features: int, lam: float,
                       alpha: float, epsilon: float,
                       candidate_path: str) -> Element:
        """Ad-hoc factored-matrix serialization: the PMML carries pointers
        to the X/ Y/ artifact dirs plus the ID lists
        (reference: mfModelToPMML :430-473)."""
        save_features(store.join(candidate_path, "X"), model.user_ids, model.X)
        save_features(store.join(candidate_path, "Y"), model.item_ids, model.Y)
        doc = pmml_io.build_skeleton_pmml()
        pmml_io.add_extension(doc, "X", "X/")
        pmml_io.add_extension(doc, "Y", "Y/")
        pmml_io.add_extension(doc, "features", features)
        pmml_io.add_extension(doc, "lambda", lam)
        pmml_io.add_extension(doc, "implicit", self.implicit)
        if self.implicit:
            pmml_io.add_extension(doc, "alpha", alpha)
        pmml_io.add_extension(doc, "logStrength", self.log_strength)
        if self.log_strength:
            pmml_io.add_extension(doc, "epsilon", epsilon)
        if model.rescue is not None:
            # the generation records HOW it trained: precision rung and
            # any regularization escalation the rescue ladder took
            pmml_io.add_extension(doc, "rescue", json.dumps(model.rescue))
        pmml_io.add_extension_content(doc, "XIDs", model.user_ids)
        pmml_io.add_extension_content(doc, "YIDs", model.item_ids)
        return doc

    # -- evaluate -----------------------------------------------------------

    def evaluate(self, model: Element, candidate_path: str,
                 test_data, train_data) -> float:
        x_ids, X = load_features(store.join(candidate_path, "X"))
        y_ids, Y = load_features(store.join(candidate_path, "Y"))
        uidx = {u: j for j, u in enumerate(x_ids)}
        iidx = {i: j for j, i in enumerate(y_ids)}

        epsilon = float("nan")
        if self.log_strength:
            epsilon = float(pmml_io.get_extension_value(model, "epsilon"))
        events = als_common.parse_events(test_data, self.decay_factor,
                                         self.decay_zero_threshold)
        test = als_common.aggregate(events, self.implicit,
                                    self.log_strength, epsilon)
        # keep only test pairs whose user and item exist in the model
        users, items, values = [], [], []
        for u_i, i_i, v in zip(test.users, test.items, test.values):
            u_id = test.user_ids[u_i]
            i_id = test.item_ids[i_i]
            if u_id in uidx and i_id in iidx:
                users.append(uidx[u_id])
                items.append(iidx[i_id])
                values.append(v)
        if not users:
            return 0.0 if self.implicit else float("-inf")
        users = np.asarray(users, dtype=np.int32)
        items = np.asarray(items, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if self.implicit:
            auc = evaluation.area_under_curve(X, Y, users, items)
            _log.info("AUC: %s", auc)
            return auc
        err = evaluation.rmse(X, Y, users, items, values)
        _log.info("RMSE: %s", err)
        return -err

    # -- pre-publish integrity ----------------------------------------------

    def validate_model(self, model: Element, candidate_path: str) -> bool:
        """The ARTIFACTS must be fully finite before the candidate is
        eligible to win publication: this validates what consumers will
        actually read (the in-memory factors are gated separately and
        cheaply in build_model), so a write-path corruption cannot ship.
        Cost is one load per candidate — the same class evaluate()
        already pays, and training dwarfs both."""
        for side in ("X", "Y"):
            _, matrix = load_features(store.join(candidate_path, side))
            if not is_finite_array(matrix):
                _log.warning("Candidate at %s has non-finite %s factors; "
                             "rejecting", candidate_path, side)
                return False
        return True

    # -- publish ------------------------------------------------------------

    def can_publish_additional_model_data(self) -> bool:
        return True

    def prepare_model_ref_payload(self, model, model_path: str,
                                  new_data, past_data) -> str:
        """Sharded distribution (ISSUE 10 tentpole): a too-large model
        publishes per-slice item-factor artifacts + a manifest next to
        the PMML, and the MODEL-REF record carries the (slim) manifest
        so every consumer bulk-loads its murmur2 slices instead of
        replaying the full UP stream.  Known-items ride with the
        user-side artifact, so the whole per-row stream is replaced.
        Any write failure falls back to the bare-path payload — the
        UP stream then publishes as before (publish_additional checks
        for the manifest's presence, so the two stay consistent)."""
        if self.publish_slices < 1 or model is None:
            return model_path
        model_dir = model_path.rsplit("/", 1)[0]
        try:
            y_ids, Y = load_features(
                store.join(model_dir, pmml_io.get_extension_value(model, "Y")))
            x_ids, X = load_features(
                store.join(model_dir, pmml_io.get_extension_value(model, "X")))
            known = None
            if not self.no_known_items:
                all_events = als_common.parse_events(
                    list(new_data) + list(past_data), 1.0, 0.0)
                known = als_common.build_known_items(all_events)
            ann = None
            if self.publish_ann_index and len(y_ids):
                from ...ops import ann as ops_ann
                from . import ivf
                centroids = ivf.train_generation_centroids(
                    Y, self.ann_config)
                cells = ops_ann.assign_cells(Y, centroids)
                ann = (centroids, cells)
            slim = slices.publish_sliced(model_dir, y_ids, Y, x_ids, X,
                                         known, self.publish_slices,
                                         ann=ann)
            _log.info("Published sharded manifest: %d slices, %d items, "
                      "%d users at %s", self.publish_slices, len(y_ids),
                      len(x_ids), model_dir)
            return slices.model_ref_message(model_path, model_dir, slim)
        except OSError:
            _log.warning("Sharded slice publish failed; falling back to "
                         "the bare MODEL-REF + UP stream", exc_info=True)
            return model_path

    def publish_additional_model_data(self, model: Element, new_data, past_data,
                                      model_path: str,
                                      model_update_topic: TopicProducer) -> None:
        """Stream every factor row as an "UP" message — items first so
        user endpoints return complete results once they stop 404ing
        (reference: publishAdditionalModelData :287-319).  When the
        generation published a sharded manifest (prepare_model_ref
        wrote slices + X-with-known-items next to the model), the
        stream is fully replaced by bulk slice loads at the consumers
        and is skipped here — O(catalog) publish AND load both go."""
        if self.publish_slices >= 1 and store.exists(
                store.join(model_path, slices.MANIFEST_FILE)):
            _log.info("Sharded manifest present at %s; skipping the "
                      "Y/X UP stream", model_path)
            return
        y_rel = pmml_io.get_extension_value(model, "Y")
        y_ids, Y = load_features(store.join(model_path, y_rel))
        for id_, row in zip(y_ids, Y):
            model_update_topic.send(KEY_UP, text_utils.join_json(
                ["Y", id_, [float(v) for v in row]]))

        x_rel = pmml_io.get_extension_value(model, "X")
        x_ids, X = load_features(store.join(model_path, x_rel))
        if self.no_known_items:
            for id_, row in zip(x_ids, X):
                model_update_topic.send(KEY_UP, text_utils.join_json(
                    ["X", id_, [float(v) for v in row]]))
        else:
            all_events = als_common.parse_events(
                list(new_data) + list(past_data), 1.0, 0.0)
            known = als_common.build_known_items(all_events)
            for id_, row in zip(x_ids, X):
                model_update_topic.send(KEY_UP, text_utils.join_json(
                    ["X", id_, [float(v) for v in row],
                     sorted(known.get(id_, ()))]))

    # -- split --------------------------------------------------------------

    def split_new_data_to_train_test(self, new_data):
        """Split solely on time: earliest (1 - test_fraction) of the
        timestamp range trains, the most recent tail tests
        (reference: splitNewDataToTrainTest :326-343)."""
        def ts(km: KeyMessage) -> int:
            return als_common.parse_timestamp(
                text_utils.parse_input_line(km.message))

        stamps = [ts(km) for km in new_data]
        min_t, max_t = min(stamps), max(stamps)
        boundary = max_t - self.test_fraction * (max_t - min_t)
        train = [km for km, t in zip(new_data, stamps) if t < boundary]
        test = [km for km, t in zip(new_data, stamps) if t >= boundary]
        return train, test
