"""ALS model evaluation: RMSE (explicit) and mean per-user AUC (implicit).

Reference: app/oryx-app-mllib/src/main/java/com/cloudera/oryx/app/batch/
mllib/als/Evaluation.java — rmse :49-63 (predict test pairs, root mean
squared diff) and areaUnderCurve :70-136 (per-user AUC: sample about as
many random negative items as the user has positives, count how often a
positive outranks a negative, average over users).

TPU-native: predictions for all test pairs and all sampled negatives are
two batched gather+dot kernels; only the light per-user pairwise
counting runs on host.
"""

from __future__ import annotations

import numpy as np

from ...common.rand import RandomManager
from .trainer import predict_pairs

__all__ = ["rmse", "area_under_curve"]


def rmse(X: np.ndarray, Y: np.ndarray,
         users: np.ndarray, items: np.ndarray, values: np.ndarray) -> float:
    preds = predict_pairs(X, Y, users, items)
    return float(np.sqrt(np.mean((preds - values) ** 2)))


def area_under_curve(X: np.ndarray, Y: np.ndarray,
                     users: np.ndarray, items: np.ndarray) -> float:
    """Mean per-user AUC over (user, positive-item) test pairs.

    All positive and all sampled-negative predictions are computed in
    TWO batched device calls; only the light pairwise counting runs on
    host per user.
    """
    if len(users) == 0:
        return 0.0
    rng = RandomManager.random()
    all_items = np.unique(items)

    # group positives per user
    order = np.argsort(users, kind="stable")
    su, si = users[order], items[order]
    uniq_users, starts = np.unique(su, return_index=True)
    ends = np.append(starts[1:], len(su))

    # sample about as many negatives as positives per user (reference:
    # with replacement from the distinct item universe, skipping the
    # user's positives, bounded by the universe size)
    neg_users: list[int] = []
    neg_items: list[int] = []
    neg_bounds = [0]
    for u, lo, hi in zip(uniq_users, starts, ends):
        pos_items = set(si[lo:hi].tolist())
        num_pos = hi - lo
        negatives: list[int] = []
        for _ in range(len(all_items)):
            if len(negatives) >= num_pos:
                break
            cand = int(all_items[rng.integers(len(all_items))])
            if cand not in pos_items:
                negatives.append(cand)
        neg_users.extend([int(u)] * len(negatives))
        neg_items.extend(negatives)
        neg_bounds.append(len(neg_items))

    pos_scores_all = predict_pairs(X, Y, su, si)
    neg_scores_all = (predict_pairs(
        X, Y, np.asarray(neg_users, dtype=np.int32),
        np.asarray(neg_items, dtype=np.int32))
        if neg_items else np.zeros(0, dtype=np.float32))

    aucs = []
    for idx, (lo, hi) in enumerate(zip(starts, ends)):
        neg = neg_scores_all[neg_bounds[idx]:neg_bounds[idx + 1]]
        if len(neg) == 0:
            aucs.append(0.0)
            continue
        pos = pos_scores_all[lo:hi]
        correct = np.sum(pos[:, None] > neg[None, :])
        aucs.append(float(correct) / (len(pos) * len(neg)))
    return float(np.mean(aucs))
