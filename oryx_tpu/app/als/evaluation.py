"""ALS model evaluation: RMSE (explicit) and mean per-user AUC (implicit).

Reference: app/oryx-app-mllib/src/main/java/com/cloudera/oryx/app/batch/
mllib/als/Evaluation.java — rmse :49-63 (predict test pairs, root mean
squared diff) and areaUnderCurve :70-136 (per-user AUC: sample about as
many random negative items as the user has positives, count how often a
positive outranks a negative, average over users).

TPU-native: predictions for all test pairs and all sampled negatives are
two gather+dot kernels; the pairwise positive>negative comparison is a
padded (U, P, N) broadcast on device instead of a per-user join.
"""

from __future__ import annotations

import numpy as np

from ...common.rand import RandomManager
from .trainer import predict_pairs

__all__ = ["rmse", "area_under_curve"]


def rmse(X: np.ndarray, Y: np.ndarray,
         users: np.ndarray, items: np.ndarray, values: np.ndarray) -> float:
    preds = predict_pairs(X, Y, users, items)
    return float(np.sqrt(np.mean((preds - values) ** 2)))


def area_under_curve(X: np.ndarray, Y: np.ndarray,
                     users: np.ndarray, items: np.ndarray) -> float:
    """Mean per-user AUC over (user, positive-item) test pairs."""
    if len(users) == 0:
        return 0.0
    rng = RandomManager.random()
    n_items = Y.shape[0]
    all_items = np.unique(items)

    # group positives per user
    order = np.argsort(users, kind="stable")
    su, si = users[order], items[order]
    uniq_users, starts = np.unique(su, return_index=True)
    ends = np.append(starts[1:], len(su))

    aucs = []
    pos_scores_all = predict_pairs(X, Y, su, si)
    for u, lo, hi in zip(uniq_users, starts, ends):
        pos_items = set(si[lo:hi].tolist())
        num_pos = hi - lo
        # sample about as many negatives as positives (reference samples
        # with replacement from the distinct item universe, skipping
        # positives, bounded by the item count)
        negatives = []
        for _ in range(len(all_items)):
            if len(negatives) >= num_pos:
                break
            cand = int(all_items[rng.integers(len(all_items))])
            if cand not in pos_items:
                negatives.append(cand)
        if not negatives:
            aucs.append(0.0)
            continue
        neg_scores = predict_pairs(
            X, Y, np.full(len(negatives), u, dtype=np.int32),
            np.asarray(negatives, dtype=np.int32))
        pos_scores = pos_scores_all[lo:hi]
        correct = np.sum(pos_scores[:, None] > neg_scores[None, :])
        total = num_pos * len(negatives)
        aucs.append(float(correct) / total if total else 0.0)
    return float(np.mean(aucs))
