"""ALS speed layer: in-memory factor model + micro-batch fold-in.

Reference: app/oryx-app/src/main/java/com/cloudera/oryx/app/speed/als/
ALSSpeedModel.java:40-183 (X/Y partitioned vectors, expected-ID
accounting, cached XtX/YtY solvers) and ALSSpeedModelManager.java:60-231
(consume MODEL/UP; buildUpdates: timestamp-sort, delete-aware aggregate,
then one fold-in solve per event on a parallelStream).

TPU-native: buildUpdates aggregates the micro-batch on host, then folds
ALL user-side updates in one batched device solve and all item-side
updates in another (ops/als_fold_in.fold_in_batch) — two kernel launches
per micro-batch instead of two host solves per event.
"""

from __future__ import annotations

import logging
import time
from typing import Iterable, Sequence

import numpy as np

from ...api.speed import AbstractSpeedModelManager, SpeedModel
from ...common import pmml as pmml_io
from ...common import text as text_utils
from ...common.config import Config
from ...common.lang import RateLimitCheck
from ...kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP, KeyMessage
from ...ops import als_fold_in
from ..pmml_utils import read_pmml_from_update_key_message
from . import common as als_common
from . import slices
from .factor_model import FactorModelBase

_log = logging.getLogger(__name__)

__all__ = ["ALSSpeedModel", "ALSSpeedModelManager"]


class ALSSpeedModel(FactorModelBase, SpeedModel):
    """User/item factor stores with cached Gramian solvers."""

    def __init__(self, features: int, implicit: bool, log_strength: bool,
                 epsilon: float):
        super().__init__(features, implicit)
        self.log_strength = log_strength
        self.epsilon = epsilon

    def __repr__(self):  # pragma: no cover
        return (f"ALSSpeedModel[features:{self.features}, "
                f"X:({len(self.X)} users), Y:({len(self.Y)} items)]")


class ALSSpeedModelManager(AbstractSpeedModelManager):
    """Consumes MODEL/UP messages; folds new input into factor deltas."""

    def __init__(self, config: Config):
        self.model: ALSSpeedModel | None = None
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.min_model_load_fraction = config.get_double(
            "oryx.speed.min-model-load-fraction")
        if not 0.0 <= self.min_model_load_fraction <= 1.0:
            raise ValueError("min-model-load-fraction must be in [0,1]")
        # ring-sharded fold-in (oryx.speed.shard = "i/N"): the model
        # state stays FULL — Gramian solvers need the whole catalog and
        # the consume thread applies every UP/MODEL record — but
        # build_updates folds only events whose ITEM this worker owns
        # on the serving murmur2 ring, so N workers split the fold-in
        # work by item slice exactly as replicas split scoring
        shard_spec = config.get_optional_string("oryx.speed.shard")
        if shard_spec:
            from ...cluster.sharding import parse_shard_spec
            self.shard_index, self.shard_count = parse_shard_spec(shard_spec)
        else:
            self.shard_index, self.shard_count = 0, 1
        self.skipped_remote_events = 0
        self._log_rate_limit = RateLimitCheck(60.0)
        # integrity counters (mirrors the serving manager)
        self.rejected_updates = 0
        self.rejected_models = 0
        # sharded model distribution (slices.py): the speed layer folds
        # against the FULL catalog, so it bulk-loads every slice — far
        # cheaper than parsing the per-row UP stream the sharded
        # publisher no longer sends
        self.slice_loads = 0
        self.slice_load_fallbacks = 0
        self.model_load_s = 0.0

    # -- consume -------------------------------------------------------------

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == KEY_UP:
            if self.model is None:
                return  # no model to interpret with yet
            parsed = als_common.parse_up_update(message,
                                                self.model.features)
            if parsed is None:
                # malformed, wrong-dimension, or non-finite payload
                # refused at the trust boundary (shared gate:
                # als_common.parse_up_update)
                self.rejected_updates += 1
                return
            kind, id_, vector, _extras = parsed
            if kind == "X":
                self.model.set_user_vector(id_, vector)
            elif kind == "Y":
                self.model.set_item_vector(id_, vector)
            else:
                raise ValueError(f"Bad message: {message}")
            if self._log_rate_limit.test():
                _log.info("%s", self.model)
        elif key in (KEY_MODEL, KEY_MODEL_REF):
            _log.info("Loading new model")
            t_model = time.monotonic()
            model_dir = manifest = None
            if key == KEY_MODEL_REF:
                path, model_dir, manifest = slices.parse_model_ref(message)
                if model_dir is None:
                    model_dir = path.rsplit("/", 1)[0]
            pmml = read_pmml_from_update_key_message(key, message)
            if pmml is None:
                self.rejected_models += 1
                _log.warning("Model document unavailable or corrupt; "
                             "keeping current model")
                return
            try:
                features = int(pmml_io.get_extension_value(pmml, "features"))
            except (TypeError, ValueError):
                self.rejected_models += 1
                _log.warning("Model document failed validation; keeping "
                             "current model")
                return
            implicit = pmml_io.get_extension_value(pmml, "implicit") == "true"
            log_strength = pmml_io.get_extension_value(pmml, "logStrength") == "true"
            epsilon = (float(pmml_io.get_extension_value(pmml, "epsilon"))
                       if log_strength else float("nan"))
            if self.model is None or features != self.model.features:
                _log.warning("No previous model, or # features changed; "
                             "creating new one")
                self.model = ALSSpeedModel(features, implicit, log_strength,
                                           epsilon)
            x_ids = pmml_io.get_extension_content(pmml, "XIDs") or []
            y_ids = pmml_io.get_extension_content(pmml, "YIDs") or []
            self.model.set_expected_ids(x_ids, y_ids)
            self.model.retain_recent_and_user_ids(x_ids)
            self.model.retain_recent_and_item_ids(y_ids)
            if manifest is not None:
                self._load_from_manifest(model_dir, manifest)
                self.model_load_s = round(time.monotonic() - t_model, 6)
            _log.info("Model updated: %s", self.model)
        else:
            raise ValueError(f"Bad key: {key}")

    def _load_from_manifest(self, model_dir: str, manifest: dict) -> None:
        """Bulk-load EVERY slice plus the user artifact (the speed
        model is never sharded); a bad slice fails closed to the
        monolithic artifacts — same contract as the serving manager."""
        try:
            features = self.model.features
            for entry in manifest["slices"]:
                ids, matrix, _ordinals = slices.read_slice(
                    model_dir, entry, features)
                if ids:
                    self.model.bulk_load_items(ids, matrix)
            x_ids, X, _known = slices.read_x_known(
                model_dir, manifest["x"], features)
            if x_ids:
                self.model.bulk_load_users(x_ids, X)
            self.slice_loads += len(manifest["slices"])
        except (slices.SliceIntegrityError, OSError, KeyError, IndexError,
                TypeError, ValueError) as e:
            self.slice_load_fallbacks += 1
            _log.warning("Speed slice load failed (%s); falling back to "
                         "the monolithic artifacts", e)
            from .update import load_features
            from ...common import store
            try:
                y_ids2, Y = load_features(store.join(model_dir, "Y"))
                if y_ids2:
                    self.model.bulk_load_items(y_ids2, Y)
                x_ids2, X2 = load_features(store.join(model_dir, "X"))
                if x_ids2:
                    self.model.bulk_load_users(x_ids2, X2)
            except (OSError, ValueError) as e2:
                _log.error("Monolithic artifact fallback also failed "
                           "(%s); speed model stays below the fold-in "
                           "gate until the store returns", e2)

    # -- produce -------------------------------------------------------------

    def build_updates(self, new_data: Sequence[KeyMessage]) -> Iterable[str]:
        model = self.model
        if model is None or model.get_fraction_loaded() < self.min_model_load_fraction:
            return []
        model.precompute_solvers()

        events = als_common.parse_events(new_data)
        if self.shard_count > 1:
            from ...cluster.sharding import is_local_item
            owned = [ev for ev in events
                     if is_local_item(ev[1], self.shard_index,
                                      self.shard_count)]
            self.skipped_remote_events += len(events) - len(owned)
            events = owned
        agg = als_common.aggregate(events, model.implicit,
                                   model.log_strength, model.epsilon)
        if len(agg.values) == 0:
            return []

        # get() returns None (rather than raising) while the Gramian is
        # still singular — i.e. not enough data yet
        xtx = model.cached_xtx_solver.get(blocking=True)
        yty = model.cached_yty_solver.get(blocking=True)
        if xtx is None or yty is None:
            _log.info("No solver available yet for model; skipping inputs")
            return []

        n = len(agg.values)
        k = model.features
        xu = np.full((n, k), np.nan, dtype=np.float32)
        yi = np.full((n, k), np.nan, dtype=np.float32)
        user_names = [agg.user_ids[u] for u in agg.users]
        item_names = [agg.item_ids[i] for i in agg.items]
        for j, (u_name, i_name) in enumerate(zip(user_names, item_names)):
            xv = model.get_user_vector(u_name)
            if xv is not None:
                xu[j] = xv
            yv = model.get_item_vector(i_name)
            if yv is not None:
                yi[j] = yv

        # both sides, each one batched device solve
        new_xu, x_valid = als_fold_in.fold_in_batch(
            yty, agg.values, xu, yi, model.implicit)
        new_yi, y_valid = als_fold_in.fold_in_batch(
            xtx, agg.values, yi, xu, model.implicit)

        out: list[str] = []
        for j in range(n):
            if x_valid[j]:
                out.append(self._to_update_json(
                    "X", user_names[j], new_xu[j], item_names[j]))
            if y_valid[j]:
                out.append(self._to_update_json(
                    "Y", item_names[j], new_yi[j], user_names[j]))
        return out

    def _to_update_json(self, matrix: str, id_: str, vector: np.ndarray,
                        other_id: str) -> str:
        vec = [float(v) for v in vector]
        if self.no_known_items:
            return text_utils.join_json([matrix, id_, vec])
        return text_utils.join_json([matrix, id_, vec, [other_id]])
