"""IVF ANN serving index: coarse centroid partition + int8 residual
scoring of the ``nprobe`` nearest cells (ROADMAP item 1 — the catalog
scale axis).

PR 3's measured-cost router showed LSH often *loses* to the exact int8
phase-A kernel at 50 features: the Hamming mask still streams the whole
item matrix and only thins the VPU work.  An IVF index attacks the HBM
bytes themselves — the one cost the roofline says matters at 10M+
items: a k-means coarse quantizer (``ops/ann.py``, reusing the k-means
app's assignment kernel shape) partitions the catalog into cells, the
items are laid out cell-contiguously in an int8 mirror, and a query
scores ONLY the blocks of its ``nprobe`` nearest cells — streaming
``nprobe/cells`` of the catalog instead of all of it.

Exactness discipline is inherited wholesale from the int8 phase A
(docs/NUMERICS.md): quantized block maxima are inflated into sound
upper bounds, selection runs on the bounds, and phase B rescores the
winners from the exact store factors under the usual
``kth >= max(unselected bound)`` certificate.  What the certificate
can NOT see is the pruned cells — that approximation is measured
instead: at each generation load the manager samples queries, compares
IVF answers against the exact kernel, and publishes recall@N on
``/metrics`` (``model_metrics.kernel_route.ann``).  The router refuses
to route ANN below ``oryx.als.ann.min-recall`` — the certificate is a
*gate*, not a hope.

Determinism (PR 8/PR 11 result-cache byte-identity): centroid training
is seeded, nearest-centroid assignment breaks ties by lowest index,
and the cell-contiguous layout uses a stable argsort — the same
generation always builds the same index and the same query always
returns the same bytes.  With ``nprobe == cells`` every block is
probed and the result is the exact kernel's (same phase-B rescore over
the same candidate universe).

The trainer may publish the index per slice (``slices.publish_sliced``
``ann=`` argument): centroids once per generation plus each slice's
cell assignments, so a serving replica's index build stays
O(catalog/N) — assignment rides the slice artifacts it already reads.
A corrupt/missing index artifact (chaos point ``ann-index-corrupt``)
fails CLOSED to the exact kernel with the ``ann_index_fallbacks``
counter: the replica stays servable, just not sublinear.
"""

from __future__ import annotations

import gzip
import io
import json
import logging
import math
import zlib
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...common import store
from ...ops import ann as ops_ann
from ...resilience.faults import fire as _fault

_log = logging.getLogger(__name__)

__all__ = [
    "AnnConfig", "AnnState", "AnnIndexError", "IVFMirror",
    "build_mirror", "batch_top_n_ivf", "measure_recall", "mirror_shapes",
    "publish_centroids", "read_centroids", "read_slice_cells",
    "CENTROIDS_FILE",
]

CENTROIDS_FILE = "ann-centroids.json.gz"
# probe-dimension chunk for the phase-A scan: bounds for this many
# probed 128-row blocks are computed per lax.scan step, so the live
# int8 gather stays ~B x 64 x 128 x W bytes regardless of nprobe
_PROBE_CHUNK = 64
# deterministic seeds: index builds must be a pure function of the
# generation (result-cache byte-identity), so nothing here draws from
# ambient randomness
_TRAIN_SEED = 13
_RECALL_SEED = 29


class AnnIndexError(Exception):
    """A per-slice ANN index artifact is missing, corrupt, or the
    index build failed — the caller fails CLOSED to the exact kernel
    (the replica stays servable) and counts ``ann_index_fallbacks``."""


class AnnConfig:
    """Parsed ``oryx.als.ann.*`` block (validated at boot, not hours
    later on the consumer thread)."""

    def __init__(self, enabled: bool, cells: int, nprobe: int,
                 min_recall: float, recall_at: int, recall_queries: int,
                 train_sample: int, train_iterations: int):
        if cells < 2:
            raise ValueError("oryx.als.ann.cells must be >= 2")
        if not 1 <= nprobe <= cells:
            raise ValueError("oryx.als.ann.nprobe must be in [1, cells]")
        if not 0.0 <= min_recall <= 1.0:
            raise ValueError("oryx.als.ann.min-recall must be in [0, 1]")
        if recall_at < 1 or recall_queries < 1:
            raise ValueError("oryx.als.ann recall-at and recall-queries "
                             "must be >= 1")
        if train_sample < cells or train_iterations < 1:
            raise ValueError("oryx.als.ann train-sample must be >= cells "
                             "and train-iterations >= 1")
        self.enabled = enabled
        self.cells = int(cells)
        self.nprobe = int(nprobe)
        self.min_recall = float(min_recall)
        self.recall_at = int(recall_at)
        self.recall_queries = int(recall_queries)
        self.train_sample = int(train_sample)
        self.train_iterations = int(train_iterations)

    @classmethod
    def from_config(cls, config) -> "AnnConfig":
        return cls(
            enabled=config.get_bool("oryx.als.ann.enabled"),
            cells=config.get_int("oryx.als.ann.cells"),
            nprobe=config.get_int("oryx.als.ann.nprobe"),
            min_recall=config.get_double("oryx.als.ann.min-recall"),
            recall_at=config.get_int("oryx.als.ann.recall-at"),
            recall_queries=config.get_int("oryx.als.ann.recall-queries"),
            train_sample=config.get_int("oryx.als.ann.train-sample"),
            train_iterations=config.get_int(
                "oryx.als.ann.train-iterations"))

    def route_key(self) -> tuple:
        """The ANN half of the kernel-route re-measure key: a route
        measured under one ANN shape must not be reused under
        another."""
        return (self.enabled, self.cells, self.nprobe, self.min_recall)


class AnnState:
    """Per-generation ANN state attached to the serving model: the
    trained centroids (small, survive mirror eviction) plus the
    load-time recall certificate.  The big device arrays live in the
    version-keyed mirror cache, rebuilt on demand."""

    def __init__(self, cfg: AnnConfig, centroids: np.ndarray,
                 cells: np.ndarray | None = None):
        self.cfg = cfg
        self.centroids = np.asarray(centroids, dtype=np.float32)
        # optional published full-catalog assignment aligned to the
        # builder's row order — consumed once by the FIRST mirror
        # build; later version bumps reassign on device (same
        # centroids, same argmin tie-break: same cells)
        self.cells = cells
        self.recall: float | None = None
        self.index_bytes: int = 0


# -- index layout -------------------------------------------------------------

def mirror_shapes(n_rows: int, ncells: int, bs: int) -> dict:
    """Static padded layout for an ``n_rows``-capacity store and a
    ``ncells`` partition: every cell's rows pad to whole ``bs`` blocks
    (worst case one part-empty block per cell) plus one always-empty
    sentinel block the probe table's padding points at.  Shared by the
    mirror build and the AOT warmup so warmed shapes stay lock-stepped
    with what a model load will actually build."""
    n_blocks = n_rows // bs + ncells + 1
    return {"blocks": n_blocks, "rows": n_blocks * bs}


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class IVFMirror:
    """The device-resident IVF mirror for one Y-snapshot version."""

    def __init__(self, y8p, sy_b, l1y_b, pen_i, activep, perm, cents,
                 cell_blocks, index_bytes: int):
        self.y8p = y8p                  # (Npad, W) int8, cell-contiguous
        self.sy_b = sy_b                # (nb,) f32 per-block scale
        self.l1y_b = l1y_b              # (nb,) f32 per-block max row L1
        self.pen_i = pen_i              # (nb, bs) int32 retired-row mask
        self.activep = activep          # (Npad,) bool
        self.perm = perm                # (Npad,) int32 -> original row
        self.cents = cents              # (C, W) f32 lane-padded centroids
        self.cell_blocks = cell_blocks  # (C, bpc) int32 block table
        self.index_bytes = index_bytes


@partial(jax.jit, static_argnames=("fill",))
def _permute_kernel(vecs, active, perm, valid, fill: int = 0):
    """Cell-contiguous device permutation of the store snapshot: pad
    slots (valid False) become exact-zero rows so the per-block int8
    scales/L1 norms see no garbage, and their active bit is forced
    off."""
    del fill
    yp = jnp.where(valid[:, None], jnp.take(vecs, perm, axis=0), 0)
    ap = jnp.take(active, perm) & valid
    return yp, ap


def build_mirror(vecs, active, state: AnnState, bs: int,
                 cells: np.ndarray | None = None) -> IVFMirror:
    """Build the device mirror for the live snapshot: assign every row
    to its nearest centroid (or consume a published assignment), lay
    the rows out cell-contiguously in whole ``bs`` blocks, and
    quantize the permuted matrix with the SAME per-block int8 kernel
    the unpermuted int8 phase A uses — identical bound algebra."""
    from . import serving_model as sm

    n_rows, width = int(vecs.shape[0]), int(vecs.shape[1])
    ncells = int(state.centroids.shape[0])
    if n_rows % bs:
        raise AnnIndexError(f"store capacity {n_rows} not divisible by "
                            f"the {bs}-row block size")
    if cells is None:
        cells = ops_ann.assign_cells(vecs, state.centroids)
    cells = np.asarray(cells, dtype=np.int64)
    if cells.shape != (n_rows,) or cells.min(initial=0) < 0 \
            or cells.max(initial=0) >= ncells:
        raise AnnIndexError("cell assignment does not match the store")
    shapes = mirror_shapes(n_rows, ncells, bs)
    n_blocks, n_pad = shapes["blocks"], shapes["rows"]
    counts = np.bincount(cells, minlength=ncells)
    nblocks_c = -(-counts // bs)  # ceil; empty cells own 0 blocks
    if int(nblocks_c.sum()) > n_blocks - 1:
        raise AnnIndexError("cell layout overflow")  # cannot happen
    order = np.argsort(cells, kind="stable")
    # host layout: cell c's rows occupy blocks [starts[c], +nblocks_c)
    starts = np.zeros(ncells, dtype=np.int64)
    np.cumsum(nblocks_c[:-1], out=starts[1:])
    perm = np.zeros(n_pad, dtype=np.int32)
    valid = np.zeros(n_pad, dtype=bool)
    row_starts = starts * bs
    offsets = np.arange(n_rows) - np.repeat(
        np.cumsum(np.concatenate(([0], counts[:-1]))), counts)
    slots = np.repeat(row_starts, counts) + offsets
    perm[slots] = order
    valid[slots] = True
    bpc = _pow2_ceil(max(1, int(nblocks_c.max(initial=1))))
    cell_blocks = np.full((ncells, bpc), n_blocks - 1, dtype=np.int32)
    for c in range(ncells):
        nb = int(nblocks_c[c])
        if nb:
            cell_blocks[c, :nb] = np.arange(starts[c], starts[c] + nb)
    # lane-pad the centroids once so query-cell distances and row
    # assignment see the same zero-padded geometry
    cents = np.zeros((ncells, width), dtype=np.float32)
    cents[:, :state.centroids.shape[1]] = state.centroids
    permd = jnp.asarray(perm)
    yp, ap = _permute_kernel(vecs, active, permd, jnp.asarray(valid))
    y8p, sy_b, l1y_b = sm._quantize_items_kernel(yp, bs)
    pen_i = sm._penalty_kernel_i32(ap, bs)
    del yp  # the f32/bf16 permuted copy is an intermediate only
    arrays = (y8p, sy_b, l1y_b, pen_i, ap, permd)
    index_bytes = sum(a.size * a.dtype.itemsize for a in arrays) \
        + cents.nbytes + cell_blocks.nbytes
    return IVFMirror(y8p, sy_b, l1y_b, pen_i, ap, permd,
                     jnp.asarray(cents), jnp.asarray(cell_blocks),
                     int(index_bytes))


# -- the phase-A kernel -------------------------------------------------------

@partial(jax.jit, static_argnames=("k", "bs", "ksel", "nprobe",
                                   "pchunk"))
def _ivf_top_n_kernel(Y, Q, y8p, sy_b, l1y_b, pen_i, activep, perm,
                      cents, cell_blocks, k: int, bs: int, ksel: int,
                      nprobe: int, pchunk: int):
    """IVF batched top-k: the ``nprobe`` highest-dot cells by centroid
    inner product, int8 bounds for ONLY those cells' blocks (lax.scan
    over probe chunks — the gather never materializes the probe set),
    then the standard phase-B exact rescore from the ORIGINAL store
    rows with the ``kth >= max(unselected bound)`` certificate.
    Returned indices are original row indices; rows outside the probed
    cells are simply not candidates — that pruning is what the recall
    certificate measured at generation load."""
    from .serving_model import _I8_PENALTY, _q_cast

    B = Q.shape[0]
    W = int(y8p.shape[1])
    bpc = int(cell_blocks.shape[1])
    n_blocks = int(y8p.shape[0]) // bs
    Qc = _q_cast(Q, Y)
    Qf = Qc.astype(jnp.float32)
    sq = jnp.maximum(jnp.max(jnp.abs(Qf), axis=1), 1e-30) / 127.0
    q8 = jnp.clip(jnp.round(Qf / sq[:, None]), -127, 127).astype(jnp.int8)
    l1q = jnp.sum(jnp.abs(Qf), axis=1)

    # probe cells by INNER PRODUCT with the query — the metric the
    # serving score ranks by — NOT the euclidean metric the rows were
    # assigned with.  The asymmetry is deliberate (MIPS probing): the
    # euclidean order's -||c||^2 term down-ranks exactly the
    # high-norm cells whose items dominate a dot-product top-k, a
    # measured ~0.54 -> ~0.92 recall@50 swing at 50 features
    _, probe_cells = jax.lax.top_k(
        jnp.matmul(Qf, cents.T, preferred_element_type=jnp.float32),
        nprobe)                                           # (B, nprobe)
    bi = jnp.take(cell_blocks, probe_cells,
                  axis=0).reshape(B, nprobe * bpc)        # (B, P)
    P = nprobe * bpc
    P2 = -(-P // pchunk) * pchunk
    if P2 != P:  # pad with the sentinel (always-empty) block
        bi = jnp.pad(bi, ((0, 0), (0, P2 - P)),
                     constant_values=n_blocks - 1)
    y8r = y8p.reshape(n_blocks, bs, W)

    def step(_, bc):  # bc: (B, pchunk) block ids
        blk = jnp.take(y8r, bc, axis=0)                # (B, pc, bs, W)
        s = jnp.einsum("bw,bpcw->bpc", q8, blk,
                       preferred_element_type=jnp.int32)
        s = s + jnp.take(pen_i, bc, axis=0)
        return None, s.max(-1)                          # (B, pc) int32

    _, ms = jax.lax.scan(step, None,
                         jnp.transpose(bi.reshape(B, P2 // pchunk,
                                                  pchunk), (1, 0, 2)))
    m_int = jnp.transpose(ms, (1, 0, 2)).reshape(B, P2)
    # sound upper bound on each probed block's exact max score — the
    # int8 phase-A algebra verbatim (docs/NUMERICS.md)
    syg = jnp.take(sy_b, bi, axis=0)
    l1g = jnp.take(l1y_b, bi, axis=0)
    bound = (m_int.astype(jnp.float32) * syg * sq[:, None]
             + 0.5 * sq[:, None] * l1g
             + 0.5 * syg * l1q[:, None]
             + 0.25 * W * syg * sq[:, None])
    masked = m_int <= _I8_PENALTY // 2
    bound = jnp.where(masked | (l1q[:, None] == 0.0), -jnp.inf, bound)

    _, pi = jax.lax.approx_max_k(bound, ksel, recall_target=0.99999)
    m_rest = bound.at[jnp.arange(B)[:, None], pi].set(-jnp.inf).max(-1)
    m_guard = jnp.where(jnp.isfinite(m_rest),
                        m_rest + jnp.abs(m_rest) * 1e-4, m_rest)
    bi_sel = jnp.take_along_axis(bi, pi, axis=1)          # (B, ksel)
    rows_p = (bi_sel[:, :, None] * bs
              + jnp.arange(bs, dtype=jnp.int32)[None, None, :]
              ).reshape(B, ksel * bs)
    orig = jnp.take(perm, rows_p)                         # (B, R)
    ok = jnp.take(activep, rows_p)
    Yg = jnp.take(Y, orig, axis=0)                        # (B, R, W)
    scores = jnp.einsum("bf,brf->br", Qc, Yg,
                        preferred_element_type=jnp.float32)
    scores = jnp.where(ok, scores, -jnp.inf)
    ts, ti = jax.lax.top_k(scores, k)
    idx = jnp.take_along_axis(orig, ti, axis=1)
    cert = ts[:, k - 1] >= m_guard
    return ts, idx, cert


def batch_top_n_ivf(mirror: IVFMirror, Y, Q, k: int, bs: int,
                    ksel: int, nprobe: int):
    """Dispatch one window through the IVF kernel (async — the caller
    fetches).  ``ksel`` widens like the int8 path (selection runs on
    margin-inflated bounds) and clamps to the probe set; a probe set
    too small to even hold ``k`` rows refuses loudly so the dispatch
    chain falls to the next kind."""
    bpc = int(mirror.cell_blocks.shape[1])
    nprobe = min(nprobe, int(mirror.cell_blocks.shape[0]))
    P = nprobe * bpc
    ksel = max(ksel, -(-k // bs))
    ksel = min(ksel, P)
    if ksel * bs < k:
        raise AnnIndexError(
            f"probe set of {P} blocks cannot hold top-{k}")
    return _ivf_top_n_kernel(
        Y, Q, mirror.y8p, mirror.sy_b, mirror.l1y_b, mirror.pen_i,
        mirror.activep, mirror.perm, mirror.cents, mirror.cell_blocks,
        k, bs, ksel, nprobe, min(_PROBE_CHUNK, P))


# -- recall certificate -------------------------------------------------------

def measure_recall(model, mirror: IVFMirror, cfg: AnnConfig) -> float:
    """recall@N of the IVF path against the exact kernel on a sampled
    query set — THE per-generation certificate.  Queries are real user
    factors when the generation shipped any (the distribution recall
    actually serves), topped up with seeded standard normals; both
    paths run on the live device snapshot, so the measurement covers
    the quantizer, the layout, and the probe pruning together."""
    from . import serving_model as sm

    vecs, active, _version = model.Y.device_arrays_versioned()
    n_rows = int(vecs.shape[0])
    k = min(cfg.recall_at, max(1, len(model.Y)))
    rng = np.random.default_rng(_RECALL_SEED)
    qs: list[np.ndarray] = []
    if len(model.X):
        xv, xa, _ids = model.X.host_arrays()
        user_rows = xv[xa]
        if len(user_rows):
            take = min(cfg.recall_queries, len(user_rows))
            qs.append(np.asarray(
                user_rows[rng.permutation(len(user_rows))[:take],
                          :model.features], dtype=np.float32))
    short = cfg.recall_queries - sum(len(q) for q in qs)
    if short > 0:
        qs.append(rng.standard_normal(
            (short, model.features)).astype(np.float32))
    Q = np.concatenate(qs)
    Qd = jnp.asarray(Q)
    big, chunk = sm._stream_plan(n_rows, len(Q))
    if big and n_rows % chunk == 0 and k <= chunk:
        ex_s, ex_i = jax.device_get(sm._batch_top_n_chunked_kernel(
            vecs, Qd, active, None, None, k, chunk, 0))
    else:
        ex_s, ex_i = jax.device_get(sm._batch_top_n_kernel(
            vecs, Qd, active, k))
    bs = sm._BLOCK_ROWS
    ksel = sm._i8_ksel(min(sm._BLOCK_KSEL, n_rows // bs), n_rows, bs)
    an_s, an_i, _cert = jax.device_get(batch_top_n_ivf(
        mirror, vecs, Qd, k, bs, ksel, cfg.nprobe))
    hits = total = 0
    for b in range(len(Q)):
        truth = {int(i) for s, i in zip(ex_s[b], ex_i[b])
                 if math.isfinite(s)}
        if not truth:
            continue
        got = {int(i) for s, i in zip(an_s[b], an_i[b])
               if math.isfinite(s)}
        hits += len(truth & got)
        total += len(truth)
    return 1.0 if total == 0 else hits / total


# -- per-slice index artifacts (sharded distribution) -------------------------

def publish_centroids(model_dir: str, centroids: np.ndarray) -> dict:
    """Write the generation's centroid artifact (deterministic gzip,
    like every slice artifact) and return its manifest entry."""
    c64 = np.round(np.asarray(centroids, dtype=np.float32)
                   .astype(np.float64), 8)
    payload = _gzip_bytes(json.dumps(
        {"cells": int(c64.shape[0]), "features": int(c64.shape[1]),
         "centroids": c64.tolist()}, separators=(",", ":")))
    with store.open_write(store.join(model_dir, CENTROIDS_FILE)) as f:
        f.write(payload)
    return {"path": CENTROIDS_FILE, "bytes": len(payload),
            "crc32": zlib.crc32(payload), "cells": int(c64.shape[0])}


def _gzip_bytes(text: str) -> bytes:
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
        gz.write(text.encode("utf-8"))
    return buf.getvalue()


def _read_checked_ann(model_dir: str, entry: dict) -> bytes:
    """Checksum-verified ANN artifact bytes.  The chaos point
    ``ann-index-corrupt`` models a corrupt/missing per-slice index
    artifact (docs/RESILIENCE.md): the manager fails CLOSED to the
    exact kernel with the ``ann_index_fallbacks`` counter — the
    replica stays servable, just not sublinear."""
    _fault("ann-index-corrupt", error=lambda: AnnIndexError(
        f"injected corrupt ANN index artifact at {entry.get('path')}"))
    path = store.join(model_dir, entry["path"])
    try:
        with store.open_read(path) as f:
            payload = f.read()
    except OSError as e:
        raise AnnIndexError(f"unreadable ANN artifact {path}: {e}") from e
    if zlib.crc32(payload) != int(entry["crc32"]):
        raise AnnIndexError(f"checksum mismatch for {path}")
    return payload


def read_centroids(model_dir: str, entry: dict) -> np.ndarray:
    try:
        with gzip.open(io.BytesIO(_read_checked_ann(model_dir, entry)),
                       "rt", encoding="utf-8") as f:
            doc = json.load(f)
        c = np.asarray(doc["centroids"], dtype=np.float32)
        if c.shape != (int(doc["cells"]), int(doc["features"])) \
                or not np.isfinite(c).all():
            raise ValueError(f"bad centroid shape {c.shape}")
    except AnnIndexError:
        raise
    except (OSError, EOFError, ValueError, KeyError, TypeError) as e:
        raise AnnIndexError(f"undecodable centroid artifact: {e}") from e
    return c


def read_slice_cells(model_dir: str, entry: dict) -> list[int]:
    """One slice's per-row cell assignments, aligned to the slice
    artifact's row order."""
    try:
        with gzip.open(io.BytesIO(_read_checked_ann(model_dir, entry)),
                       "rt", encoding="utf-8") as f:
            cells = json.load(f)
        if not isinstance(cells, list) \
                or len(cells) != int(entry["rows"]):
            raise ValueError(
                f"{len(cells)} cells, manifest says {entry['rows']}")
    except AnnIndexError:
        raise
    except (OSError, EOFError, ValueError, KeyError, TypeError) as e:
        raise AnnIndexError(f"undecodable cell artifact: {e}") from e
    return [int(c) for c in cells]


def train_generation_centroids(Y, cfg: AnnConfig) -> np.ndarray:
    """The generation's coarse quantizer: k-means over a seeded sample
    of the item factors (deterministic — same factors, same
    centroids)."""
    Y = np.asarray(Y, dtype=np.float32)
    rng = np.random.default_rng(_TRAIN_SEED)
    sample = Y if len(Y) <= cfg.train_sample else \
        Y[rng.permutation(len(Y))[:cfg.train_sample]]
    return ops_ann.train_centroids(sample, cfg.cells,
                                   cfg.train_iterations, _TRAIN_SEED)
