"""ALS serving model manager: replays the update topic into the
serving model.

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/als/model/ALSServingModelManager.java:45-160 — UP handling with
known-items (:70-105), solver pre-trigger at load fraction (:96-103),
MODEL/MODEL-REF handling with retain logic (:107-130),
loadRescorerProviders (:142-160).
"""

from __future__ import annotations

import logging
import time

import numpy as np

from ...api.serving import AbstractServingModelManager
from ...cluster.membership import KEY_HEARTBEAT
from ...cluster.sharding import is_local_item, parse_shard_spec
from ...common import pmml as pmml_io
from ...common import store
from ...common.config import Config
from ...common.lang import RateLimitCheck
from ...kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP
from ..pmml_utils import read_pmml_from_update_key_message
from . import common as als_common
from . import ivf
from . import slices
from .rescorer import load_rescorer_providers
from .serving_model import ALSServingModel

_log = logging.getLogger(__name__)

__all__ = ["ALSServingModelManager"]


class ALSServingModelManager(AbstractServingModelManager):

    def __init__(self, config: Config):
        super().__init__(config)
        self.model: ALSServingModel | None = None
        self._triggered_solver = False
        self.rescorer_provider = load_rescorer_providers(
            config.get_optional_string("oryx.als.rescorer-provider-class"))
        self.sample_rate = config.get_double("oryx.als.sample-rate")
        self.factor_dtype = config.get_string("oryx.als.factor-dtype")
        # P4/P5 scale-out: shard the item matrix over a device mesh
        # (oryx.serving.api.item-shards; 1 = single-chip scan)
        self.item_shards = config.get_int("oryx.serving.api.item-shards")
        self.int8_selection = config.get_string(
            "oryx.serving.api.int8-selection")
        if self.int8_selection not in ("auto", "true", "false"):
            raise ValueError("int8-selection must be auto/true/false")
        self.fold_scan = config.get_string("oryx.serving.api.fold-scan")
        if self.fold_scan not in ("auto", "true", "false"):
            raise ValueError("fold-scan must be auto/true/false")
        # IVF ANN serving path (oryx.als.ann.*, ISSUE 18): parsed and
        # validated at boot like every other serving knob
        self.ann_config = ivf.AnnConfig.from_config(config)
        if self.item_shards < 1 or (self.item_shards
                                    & (self.item_shards - 1)):
            raise ValueError("item-shards must be a power of two >= 1")
        # fail at boot, not hours later on the consumer thread when the
        # first MODEL message finally constructs the serving model
        from .feature_vectors import resolve_dtype
        resolve_dtype(self.factor_dtype)
        self.min_model_load_fraction = config.get_double(
            "oryx.serving.min-model-load-fraction")
        if not 0.0 < self.sample_rate <= 1.0:
            raise ValueError("sample-rate must be in (0,1]")
        self._log_rate_limit = RateLimitCheck(60.0)
        # integrity counters: how many poison payloads this consumer
        # refused instead of absorbing into the serving model
        self.rejected_updates = 0
        self.rejected_models = 0
        # -- serving-cluster state (oryx_tpu/cluster/) -------------------
        # catalog shard this replica materializes: Y vectors whose id
        # hashes elsewhere are skipped (the user store and known-items
        # stay FULL — they are needed for local exclusion and are tiny
        # next to the item matrix).  "0/1" = the whole catalog, i.e.
        # plain single-node serving.
        spec = (config.get_optional_string("oryx.cluster.shard")
                if config.get_bool("oryx.cluster.enabled") else None)
        self.shard_index, self.shard_count = parse_shard_spec(spec or "0/1")
        # accepted MODEL/MODEL-REF documents since replay offset 0 —
        # the replica's model GENERATION, identical across replicas
        # (the update topic is totally ordered), carried in heartbeats
        # so the router never routes to a replica serving older state
        self.generation = 0
        # item id -> first-appearance index in the Y update stream: the
        # cluster's canonical tie-break ordinal (cluster/merge.py),
        # identical on every replica for the same topic replay.
        # Counts EVERY Y id seen, including ones this shard skips.
        self.item_ordinals: dict[str, int] = {}
        # next ordinal to assign.  NOT len(item_ordinals): a
        # slice-loaded replica holds ordinals for its LOCAL slices only
        # (slices carry the global index of each row), so the counter
        # must advance from the manifest's TOTAL item count — every
        # replica then assigns the same ordinal to the same
        # post-publish UP id regardless of which slices it loaded.
        self._ordinal_next = 0
        # Y vectors skipped as non-local (observability)
        self.skipped_remote_items = 0
        # -- sharded model distribution (slices.py) ----------------------
        # slices bulk-loaded, artifact bytes read, and fallbacks to the
        # monolithic artifacts (missing/corrupt slice, incompatible
        # ring) — surfaced as gauges on /metrics by the serving layer
        self.slice_loads = 0
        self.slice_load_fallbacks = 0
        self.model_slice_bytes = 0
        # seconds from MODEL(-REF) receipt to a servable model: the
        # slice path stamps it when the bulk load finishes; the replay
        # path stamps it when the UP stream crosses the load-fraction
        # gate.  THE number sharded distribution exists to shrink.
        self.model_load_s = 0.0
        self._model_received_at: float | None = None
        # sum of the owned slices' manifest Gramians: /shard/yty
        # answers from it without a device scan until a Y write lands
        self._slice_yty: "object | None" = None
        # -- IVF ANN index (ivf.py) --------------------------------------
        # device bytes pinned by the current generation's IVF mirror
        # and how many generations failed CLOSED to the exact kernel
        # (corrupt artifact / failed build / failed recall measurement)
        # — surfaced as gauges on /metrics by the serving layer
        self.ann_index_bytes = 0
        self.ann_index_fallbacks = 0
        # per-generation published-index state collected during the
        # slice load, consumed by _maybe_build_ann
        self._ann_centroid_entry: dict | None = None
        self._ann_cells_by_id: dict[str, int] = {}
        self._ann_artifacts_broken = False

    def get_model(self) -> ALSServingModel | None:
        return self.model

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == KEY_UP:
            model = self.model
            if model is None:
                return  # no model to interpret with yet
            parsed = als_common.parse_up_update(message, model.features)
            if parsed is None:
                # malformed, wrong-dimension, or non-finite payload
                # refused at the trust boundary (shared gate:
                # als_common.parse_up_update)
                self.rejected_updates += 1
                return
            kind, id_, vector, extras = parsed
            if kind == "X":
                model.set_user_vector(id_, vector)
                if extras is not None:
                    model.add_known_items(id_, [str(i) for i in extras])
            elif kind == "Y":
                # ordinal BEFORE the shard filter: the canonical
                # tie-break must agree across replicas that each skip
                # different ids.  The counter advances for EVERY Y
                # record — not every new id — because a slice-loaded
                # replica holds only its LOCAL slices' ordinals and
                # cannot tell a remote MANIFEST item from a genuinely
                # new one: advancing per record keeps the counter (and
                # therefore every new id's ordinal) identical on every
                # replica of the totally ordered topic, whatever subset
                # each loaded.  setdefault keeps an already-known id's
                # ordinal stable; the skipped slots are harmless gaps
                # (ordinals only need a shared total order).
                self.item_ordinals.setdefault(id_, self._ordinal_next)
                self._ordinal_next += 1
                if is_local_item(id_, self.shard_index, self.shard_count):
                    model.set_item_vector(id_, vector)
                    # a live Y write outdates the manifest's partial
                    # Gramian: /shard/yty scans again until next load
                    self._slice_yty = None
                else:
                    self.skipped_remote_items += 1
            else:
                raise ValueError(f"Bad message: {message}")
            # load-fraction trigger OUTSIDE the log rate limiter: a
            # bulk replay that finishes inside one 60 s window must
            # not serve a minute of live traffic without solvers or a
            # measured kernel route (the `not triggered` bool keeps
            # the post-trigger per-UP cost at one attribute read)
            if (not self._triggered_solver
                    and model.get_fraction_loaded()
                    >= self.min_model_load_fraction):
                self._triggered_solver = True
                # the replay path's load clock: MODEL receipt -> the UP
                # stream crossing the serving gate (the slice path
                # stamps its own, much earlier, moment)
                if self._model_received_at is not None:
                    self.model_load_s = round(
                        time.monotonic() - self._model_received_at, 6)
                    self._model_received_at = None
                model.precompute_solvers()
                # replay-loaded factors: build the IVF index + measure
                # the recall certificate before routing, so the route
                # below is measured against the chain ANN may join
                self._maybe_build_ann(None)
                # with the factors loaded, time each eligible kernel
                # path for the live shape so serving routes by
                # measured cost (re-measures only if the store's
                # padded capacity changed since)
                model.refresh_route()
            if self._log_rate_limit.test():
                _log.info("%s", model)
        elif key in (KEY_MODEL, KEY_MODEL_REF):
            _log.info("Loading new model")
            t_model = time.monotonic()
            model_dir = manifest = None
            if key == KEY_MODEL_REF:
                # manifest-carrying envelope (slices.py): the record
                # names the per-slice artifacts this replica may
                # bulk-load instead of replaying a full UP stream
                path, model_dir, manifest = slices.parse_model_ref(message)
                if model_dir is None:
                    model_dir = path.rsplit("/", 1)[0]
            pmml = read_pmml_from_update_key_message(key, message)
            if pmml is None:
                self.rejected_models += 1
                _log.warning("Model document unavailable or corrupt; "
                             "keeping current model")
                return
            try:
                features = int(pmml_io.get_extension_value(pmml, "features"))
            except (TypeError, ValueError):
                # parseable XML that is not a factored-model document
                # (e.g. recovered from a partially corrupt artifact)
                self.rejected_models += 1
                _log.warning("Model document failed validation; keeping "
                             "current model")
                return
            implicit = pmml_io.get_extension_value(pmml, "implicit") == "true"
            if self.model is None or features != self.model.features:
                _log.warning("No previous model, or # features changed; "
                             "creating new one")
                # a REPLACEMENT model starts un-triggered: the solver
                # precompute + kernel-route measurement must re-fire at
                # ITS load-fraction threshold, not stay latched off by
                # the previous model's trigger
                self._triggered_solver = False
                self.model = ALSServingModel(
                    features, implicit, self.sample_rate,
                    self.rescorer_provider, dtype=self.factor_dtype,
                    item_shards=self.item_shards,
                    int8_selection=self.int8_selection,
                    fold_scan=self.fold_scan,
                    ann_config=self.ann_config
                    if self.ann_config.enabled else None)
            _log.info("Updating model")
            x_ids = set(pmml_io.get_extension_content(pmml, "XIDs") or [])
            y_ids = set(pmml_io.get_extension_content(pmml, "YIDs") or [])
            # sharded replica: expected-ID accounting and the Y retain
            # run over the LOCAL slice only (fraction-loaded gates on
            # what this shard will actually materialize); known-items
            # retain keeps the GLOBAL id universe — exclusion works by
            # id and must cover items other shards hold
            local_y = [i for i in y_ids
                       if is_local_item(i, self.shard_index,
                                        self.shard_count)] \
                if self.shard_count > 1 else list(y_ids)
            self.model.set_expected_ids(list(x_ids), local_y)
            self.model.retain_recent_and_known_items(list(x_ids), list(y_ids))
            self.model.retain_recent_and_user_ids(list(x_ids))
            self.model.retain_recent_and_item_ids(local_y)
            self.generation += 1
            self._model_received_at = t_model
            # a NEW generation outdates any held manifest Gramian
            # immediately (the retains above already pruned rows); a
            # successful slice load below sets the fresh one
            self._slice_yty = None
            # reset the previous generation's published-index state
            # before any load path repopulates it
            self._ann_centroid_entry = None
            self._ann_cells_by_id = {}
            self._ann_artifacts_broken = False
            if manifest is not None:
                # sharded distribution: bulk-load exactly this shard's
                # slices (O(catalog/N)); a bad slice fails closed to
                # the monolithic artifacts — ready either way
                self._load_from_manifest(model_dir, manifest)
            # IVF index build INSIDE the load clock: `model_load_s`
            # covers it (the index is part of being servable at the
            # advertised latency), and it must precede refresh_route so
            # the measured route includes the "ivf" kind
            self._maybe_build_ann(model_dir)
            if (self._model_received_at is not None
                    and self.model.get_fraction_loaded()
                    >= self.min_model_load_fraction):
                # the artifacts alone crossed the serving gate (slice
                # or fallback load): the replica is SERVABLE now —
                # stamp the load clock before the route measurement
                # and solver precompute below, which are warmup the
                # replay path also runs outside its clock
                self.model_load_s = round(time.monotonic() - t_model, 6)
                self._model_received_at = None
            # hot-swap: the new generation may have regrown the padded
            # store — refresh the measured-cost kernel route for the
            # new shape (no-op while capacity and LSH config match)
            self.model.refresh_route()
            if (not self._triggered_solver
                    and self.model.get_fraction_loaded()
                    >= self.min_model_load_fraction):
                # no UP flood follows to fire the load-fraction
                # trigger, so the solvers precompute here
                self._triggered_solver = True
                self.model.precompute_solvers()
            _log.info("Model updated: %s", self.model)
        elif key == KEY_HEARTBEAT:
            # cluster control-plane traffic on the shared update topic;
            # the layers' consume threads already filter it, this guard
            # covers direct manager drives (tests, embedding)
            return
        else:
            raise ValueError(f"Bad key: {key}")

    # -- sharded model distribution (slices.py) ------------------------------

    def _load_from_manifest(self, model_dir: str, manifest: dict) -> None:
        """Bulk-load this shard's slices + the user artifact; any
        integrity failure fails closed to :meth:`_load_full_artifacts`
        with the ``slice_load_fallbacks`` counter — a corrupt slice
        costs the O(catalog) load, never readiness."""
        try:
            ring = int(manifest["ring"])
            owned = slices.owned_slices(ring, self.shard_index,
                                        self.shard_count)
            if owned is None:
                raise slices.SliceIntegrityError(
                    f"slice ring {ring} incompatible with shard count "
                    f"{self.shard_count} (pick a ring the shard count "
                    f"divides)")
            features = self.model.features
            total_bytes = 0
            gramian = np.zeros((features, features), dtype=np.float64)
            # gramians live only in the STORE manifest (k*k floats per
            # slice would blow the topic's max message size); absence
            # just means /shard/yty scans instead
            full = slices.read_manifest(model_dir)
            grams = (full or {}).get("gramians")
            entries = {int(e["slice"]): e for e in manifest["slices"]}
            self._ann_centroid_entry = manifest.get("ann")
            for s in owned:
                entry = entries[s]
                ids, matrix, ordinals = slices.read_slice(
                    model_dir, entry, features)
                if ids:
                    self.model.bulk_load_items(ids, matrix)
                    self.item_ordinals.update(zip(ids, ordinals))
                total_bytes += int(entry.get("bytes", 0))
                if grams is not None:
                    gramian += np.asarray(grams[s], dtype=np.float64)
                self._collect_slice_ann(model_dir, entry, ids)
            x_ids, X, known = slices.read_x_known(
                model_dir, manifest["x"], features)
            if x_ids:
                self.model.bulk_load_users(x_ids, X)
                for uid, items in zip(x_ids, known):
                    if items:
                        self.model.add_known_items(uid, items)
            total_bytes += int(manifest["x"].get("bytes", 0))
            self._ordinal_next = max(self._ordinal_next,
                                     int(manifest["items"]))
            self.slice_loads += len(owned)
            self.model_slice_bytes = total_bytes
            self._slice_yty = gramian if grams is not None else None
            _log.info(
                "Slice-loaded %d/%d slices (%d items, %d users, %d "
                "bytes) for shard %d/%d", len(owned), ring,
                len(self.model.Y), len(self.model.X), total_bytes,
                self.shard_index, self.shard_count)
        except (slices.SliceIntegrityError, OSError, KeyError, IndexError,
                TypeError, ValueError) as e:
            self.slice_load_fallbacks += 1
            self._slice_yty = None
            # a failed slice load discredits the whole manifest, the
            # published index artifacts with it: the ANN build (if
            # enabled) trains locally over whatever the fallback loads
            self._ann_centroid_entry = None
            self._ann_cells_by_id = {}
            _log.warning("Slice load failed (%s); falling back to the "
                         "monolithic artifacts", e)
            self._load_full_artifacts(model_dir)

    def _load_full_artifacts(self, model_dir: str) -> None:
        """The fail-closed path: read the monolithic ``Y``/``X``
        artifacts the publisher still writes, filter to this shard,
        and assign ordinals by artifact position — exactly the state a
        full-stream replay would have built (the artifact order IS the
        stream order)."""
        from .update import load_features
        try:
            y_ids, Y = load_features(store.join(model_dir, "Y"))
            local = [j for j, iid in enumerate(y_ids)
                     if is_local_item(iid, self.shard_index,
                                      self.shard_count)]
            if local:
                self.model.bulk_load_items(
                    [y_ids[j] for j in local], Y[local])
            self.skipped_remote_items += len(y_ids) - len(local)
            for j, iid in enumerate(y_ids):
                self.item_ordinals.setdefault(iid, j)
            self._ordinal_next = max(self._ordinal_next, len(y_ids))
            x_ids, X = load_features(store.join(model_dir, "X"))
            if x_ids:
                self.model.bulk_load_users(x_ids, X)
            _log.info("Fallback-loaded monolithic artifacts: %d local "
                      "items, %d users", len(local), len(x_ids))
        except (OSError, ValueError) as e:
            # store unreachable: the replica stays below the serving
            # gate and the router routes around it — log, don't die
            _log.error("Monolithic artifact fallback also failed (%s); "
                       "replica will not reach ready until the store "
                       "returns", e)

    # -- IVF ANN index (ivf.py, ISSUE 18) ------------------------------------

    def _collect_slice_ann(self, model_dir: str, entry: dict,
                           ids: list[str]) -> None:
        """Read one owned slice's published cell assignments.  A
        corrupt/missing index artifact (chaos point
        ``ann-index-corrupt``) never fails the SLICE load — the
        factors are intact — but marks the generation's published
        index broken so ``_maybe_build_ann`` fails CLOSED to the exact
        kernel."""
        aent = entry.get("ann")
        if aent is None or not self.ann_config.enabled \
                or self._ann_artifacts_broken:
            return
        try:
            cells = ivf.read_slice_cells(model_dir, aent)
            self._ann_cells_by_id.update(zip(ids, cells))
        except ivf.AnnIndexError as e:
            self._ann_artifacts_broken = True
            _log.warning("ANN index artifact unusable (%s); this "
                         "generation will serve on the exact kernel", e)

    def _maybe_build_ann(self, model_dir: str | None) -> None:
        """Build the generation's IVF index over this replica's owned
        rows and measure its recall certificate against the exact
        kernel (``ivf.measure_recall``) — BEFORE routing, so
        ``refresh_route`` measures the chain ANN may join.  Published
        artifacts (centroids + per-slice cells) skip the local k-means
        training; any failure anywhere fails CLOSED to the exact
        kernel with ``ann_index_fallbacks`` — ANN is an optimization,
        never a readiness gate."""
        cfg = self.ann_config
        model = self.model
        if not cfg.enabled or model is None or model._item_shards > 1 \
                or len(model.Y) == 0:
            return
        try:
            if self._ann_artifacts_broken:
                raise ivf.AnnIndexError(
                    "published index artifacts unreadable")
            cells = None
            if self._ann_centroid_entry is not None \
                    and model_dir is not None:
                centroids = ivf.read_centroids(
                    model_dir, self._ann_centroid_entry)
                cells = self._published_cells()
            else:
                yv, ya, _ids = model.Y.host_arrays()
                centroids = ivf.train_generation_centroids(
                    yv[ya][:, :model.features], cfg)
            state = ivf.AnnState(cfg, centroids, cells=cells)
            model.attach_ann(state)
            vecs, active, version = model.Y.device_arrays_versioned()
            mirror = model._cached_ivf(vecs, active, version)
            state.recall = ivf.measure_recall(model, mirror, cfg)
            self.ann_index_bytes = mirror.index_bytes
            if state.recall < cfg.min_recall:
                _log.warning(
                    "IVF recall certificate FAILED for generation %d: "
                    "recall@%d %.4f < min-recall %.2f — serving stays "
                    "on the exact kernel", self.generation,
                    cfg.recall_at, state.recall, cfg.min_recall)
            else:
                _log.info(
                    "IVF index ready for generation %d: %d cells, "
                    "nprobe %d, recall@%d %.4f, %d bytes",
                    self.generation, int(state.centroids.shape[0]),
                    cfg.nprobe, cfg.recall_at, state.recall,
                    mirror.index_bytes)
        except Exception as e:  # noqa: BLE001 — fail closed to exact
            self.ann_index_fallbacks += 1
            self.ann_index_bytes = 0
            model.attach_ann(None)
            _log.warning("IVF ANN index build failed (%s); generation "
                         "%d serves on the exact kernel", e,
                         self.generation)

    def _published_cells(self) -> "np.ndarray | None":
        """Published per-slice cell assignments re-aligned to the
        store's row slots.  Partial coverage (a row the artifacts do
        not name) returns None — the mirror build assigns on device
        instead, which is always correct."""
        by_id = self._ann_cells_by_id
        if not by_id:
            return None
        row_ids = self.model.Y.row_ids()
        cells = np.zeros(len(row_ids), dtype=np.int32)
        for i, rid in enumerate(row_ids):
            if rid is None:
                continue
            c = by_id.get(rid)
            if c is None:
                return None
            cells[i] = c
        return cells

    def partial_yty(self) -> "np.ndarray | None":
        """This shard's Gramian from the manifest's per-slice partials
        — lets ``/shard/yty`` answer without a device scan — or None
        when no fresh manifest Gramian is held (replay-loaded model, a
        Y write since load, or a manifest without Gramians)."""
        g = self._slice_yty
        return None if g is None else np.asarray(g, dtype=np.float64)
