"""ALS serving model manager: replays the update topic into the
serving model.

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/als/model/ALSServingModelManager.java:45-160 — UP handling with
known-items (:70-105), solver pre-trigger at load fraction (:96-103),
MODEL/MODEL-REF handling with retain logic (:107-130),
loadRescorerProviders (:142-160).
"""

from __future__ import annotations

import logging

from ...api.serving import AbstractServingModelManager
from ...cluster.membership import KEY_HEARTBEAT
from ...cluster.sharding import is_local_item, parse_shard_spec
from ...common import pmml as pmml_io
from ...common.config import Config
from ...common.lang import RateLimitCheck
from ...kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP
from ..pmml_utils import read_pmml_from_update_key_message
from . import common as als_common
from .rescorer import load_rescorer_providers
from .serving_model import ALSServingModel

_log = logging.getLogger(__name__)

__all__ = ["ALSServingModelManager"]


class ALSServingModelManager(AbstractServingModelManager):

    def __init__(self, config: Config):
        super().__init__(config)
        self.model: ALSServingModel | None = None
        self._triggered_solver = False
        self.rescorer_provider = load_rescorer_providers(
            config.get_optional_string("oryx.als.rescorer-provider-class"))
        self.sample_rate = config.get_double("oryx.als.sample-rate")
        self.factor_dtype = config.get_string("oryx.als.factor-dtype")
        # P4/P5 scale-out: shard the item matrix over a device mesh
        # (oryx.serving.api.item-shards; 1 = single-chip scan)
        self.item_shards = config.get_int("oryx.serving.api.item-shards")
        self.int8_selection = config.get_string(
            "oryx.serving.api.int8-selection")
        if self.int8_selection not in ("auto", "true", "false"):
            raise ValueError("int8-selection must be auto/true/false")
        self.fold_scan = config.get_string("oryx.serving.api.fold-scan")
        if self.fold_scan not in ("auto", "true", "false"):
            raise ValueError("fold-scan must be auto/true/false")
        if self.item_shards < 1 or (self.item_shards
                                    & (self.item_shards - 1)):
            raise ValueError("item-shards must be a power of two >= 1")
        # fail at boot, not hours later on the consumer thread when the
        # first MODEL message finally constructs the serving model
        from .feature_vectors import resolve_dtype
        resolve_dtype(self.factor_dtype)
        self.min_model_load_fraction = config.get_double(
            "oryx.serving.min-model-load-fraction")
        if not 0.0 < self.sample_rate <= 1.0:
            raise ValueError("sample-rate must be in (0,1]")
        self._log_rate_limit = RateLimitCheck(60.0)
        # integrity counters: how many poison payloads this consumer
        # refused instead of absorbing into the serving model
        self.rejected_updates = 0
        self.rejected_models = 0
        # -- serving-cluster state (oryx_tpu/cluster/) -------------------
        # catalog shard this replica materializes: Y vectors whose id
        # hashes elsewhere are skipped (the user store and known-items
        # stay FULL — they are needed for local exclusion and are tiny
        # next to the item matrix).  "0/1" = the whole catalog, i.e.
        # plain single-node serving.
        spec = (config.get_optional_string("oryx.cluster.shard")
                if config.get_bool("oryx.cluster.enabled") else None)
        self.shard_index, self.shard_count = parse_shard_spec(spec or "0/1")
        # accepted MODEL/MODEL-REF documents since replay offset 0 —
        # the replica's model GENERATION, identical across replicas
        # (the update topic is totally ordered), carried in heartbeats
        # so the router never routes to a replica serving older state
        self.generation = 0
        # item id -> first-appearance index in the Y update stream: the
        # cluster's canonical tie-break ordinal (cluster/merge.py),
        # identical on every replica for the same topic replay.
        # Counts EVERY Y id seen, including ones this shard skips.
        self.item_ordinals: dict[str, int] = {}
        # Y vectors skipped as non-local (observability)
        self.skipped_remote_items = 0

    def get_model(self) -> ALSServingModel | None:
        return self.model

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == KEY_UP:
            model = self.model
            if model is None:
                return  # no model to interpret with yet
            parsed = als_common.parse_up_update(message, model.features)
            if parsed is None:
                # malformed, wrong-dimension, or non-finite payload
                # refused at the trust boundary (shared gate:
                # als_common.parse_up_update)
                self.rejected_updates += 1
                return
            kind, id_, vector, extras = parsed
            if kind == "X":
                model.set_user_vector(id_, vector)
                if extras is not None:
                    model.add_known_items(id_, [str(i) for i in extras])
            elif kind == "Y":
                # ordinal BEFORE the shard filter: the canonical
                # tie-break must agree across replicas that each skip
                # different ids
                self.item_ordinals.setdefault(id_,
                                              len(self.item_ordinals))
                if is_local_item(id_, self.shard_index, self.shard_count):
                    model.set_item_vector(id_, vector)
                else:
                    self.skipped_remote_items += 1
            else:
                raise ValueError(f"Bad message: {message}")
            # load-fraction trigger OUTSIDE the log rate limiter: a
            # bulk replay that finishes inside one 60 s window must
            # not serve a minute of live traffic without solvers or a
            # measured kernel route (the `not triggered` bool keeps
            # the post-trigger per-UP cost at one attribute read)
            if (not self._triggered_solver
                    and model.get_fraction_loaded()
                    >= self.min_model_load_fraction):
                self._triggered_solver = True
                model.precompute_solvers()
                # with the factors loaded, time each eligible kernel
                # path for the live shape so serving routes by
                # measured cost (re-measures only if the store's
                # padded capacity changed since)
                model.refresh_route()
            if self._log_rate_limit.test():
                _log.info("%s", model)
        elif key in (KEY_MODEL, KEY_MODEL_REF):
            _log.info("Loading new model")
            pmml = read_pmml_from_update_key_message(key, message)
            if pmml is None:
                self.rejected_models += 1
                _log.warning("Model document unavailable or corrupt; "
                             "keeping current model")
                return
            try:
                features = int(pmml_io.get_extension_value(pmml, "features"))
            except (TypeError, ValueError):
                # parseable XML that is not a factored-model document
                # (e.g. recovered from a partially corrupt artifact)
                self.rejected_models += 1
                _log.warning("Model document failed validation; keeping "
                             "current model")
                return
            implicit = pmml_io.get_extension_value(pmml, "implicit") == "true"
            if self.model is None or features != self.model.features:
                _log.warning("No previous model, or # features changed; "
                             "creating new one")
                # a REPLACEMENT model starts un-triggered: the solver
                # precompute + kernel-route measurement must re-fire at
                # ITS load-fraction threshold, not stay latched off by
                # the previous model's trigger
                self._triggered_solver = False
                self.model = ALSServingModel(
                    features, implicit, self.sample_rate,
                    self.rescorer_provider, dtype=self.factor_dtype,
                    item_shards=self.item_shards,
                    int8_selection=self.int8_selection,
                    fold_scan=self.fold_scan)
            _log.info("Updating model")
            x_ids = set(pmml_io.get_extension_content(pmml, "XIDs") or [])
            y_ids = set(pmml_io.get_extension_content(pmml, "YIDs") or [])
            # sharded replica: expected-ID accounting and the Y retain
            # run over the LOCAL slice only (fraction-loaded gates on
            # what this shard will actually materialize); known-items
            # retain keeps the GLOBAL id universe — exclusion works by
            # id and must cover items other shards hold
            local_y = [i for i in y_ids
                       if is_local_item(i, self.shard_index,
                                        self.shard_count)] \
                if self.shard_count > 1 else list(y_ids)
            self.model.set_expected_ids(list(x_ids), local_y)
            self.model.retain_recent_and_known_items(list(x_ids), list(y_ids))
            self.model.retain_recent_and_user_ids(list(x_ids))
            self.model.retain_recent_and_item_ids(local_y)
            self.generation += 1
            # hot-swap: the new generation may have regrown the padded
            # store — refresh the measured-cost kernel route for the
            # new shape (no-op while capacity and LSH config match)
            self.model.refresh_route()
            _log.info("Model updated: %s", self.model)
        elif key == KEY_HEARTBEAT:
            # cluster control-plane traffic on the shared update topic;
            # the layers' consume threads already filter it, this guard
            # covers direct manager drives (tests, embedding)
            return
        else:
            raise ValueError(f"Bad key: {key}")
