"""Device-backed feature-vector store with a dynamic ID universe.

Reference: app/oryx-app-common/src/main/java/com/cloudera/oryx/app/als/
FeatureVectors.java:28-86 (get/set vector, recent-ID tracking,
retainRecentAndIDs, getVTV), FeatureVectorsPartition.java:36 (hash map +
RW lock per partition), PartitionedFeatureVectors.java:43-222 (the
serving-time sharded matrix).

TPU-native design (the "dynamic ID universe on a static-shape device"
hard part): IDs live in a host dict mapping to rows of a padded device
array.  Single-row "UP" mutations write a host mirror and enqueue the
row; the device copy is refreshed lazily at the next read — a batched
scatter for few dirty rows, a full re-upload when many changed — so
serving reads always see a consistent device snapshot and per-event
device dispatch never happens.  Removed rows are zeroed and recycled via
a free list; capacity grows by doubling.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ...common.lang import AutoReadWriteLock

__all__ = ["FeatureVectorStore", "resolve_dtype"]

# above this fraction of dirty rows, re-upload the whole array instead of
# scattering individual rows
_FULL_UPLOAD_FRACTION = 0.5

# beyond this many rows, capacity is rounded to a multiple of this chunk
# instead of the next power of two: a 20M-item model must not allocate a
# 32M-row device array, and the chunked top-N kernel requires the row
# count to be a multiple of its scan chunk (serving_model._CHUNK_ROWS)
_LARGE_ALIGN = 1 << 17


def planned_capacity(n_rows: int, initial_capacity: int = 1024) -> int:
    """The padded row capacity a fresh store ends up with after a
    single ``bulk_load`` of ``n_rows`` vectors — the compiled leading
    dimension every serving kernel sees for a model of that size.  The
    deploy-time AOT warmup (deploy/warmup.py) uses this to lower the
    kernel ladder with the EXACT shapes a later model load produces;
    keep it in lock-step with ``__init__``/``_grow`` (and tested
    against a real bulk_load in tests/test_bench_tools.py)."""
    cap = max(16, initial_capacity)
    if n_rows > cap:
        # one _grow(min_capacity=n_rows) from the fresh store
        cap = max(cap * 2, n_rows)
    if cap > _LARGE_ALIGN:
        cap = -(-cap // _LARGE_ALIGN) * _LARGE_ALIGN
    return cap


def resolve_dtype(name) -> np.dtype:
    """Factor storage dtype from a config string.  ``bfloat16`` halves
    both host and HBM footprint (20M x 250 drops from 20 GB to 10 GB —
    the reference's largest published model, docs/docs/performance.html
    memory table) and the MXU natively multiplies bf16 with float32
    accumulation, so dot-product scores keep full precision."""
    if name is None or isinstance(name, np.dtype):
        return np.dtype(np.float32) if name is None else name
    name = str(name)
    if name in ("bfloat16", "bf16"):
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if name in ("float32", "f32"):
        return np.dtype(np.float32)
    raise ValueError(f"unsupported factor dtype: {name}")


class FeatureVectorStore:
    """Mutable {id -> float32[k]} map materialized as a device array."""

    def __init__(self, features: int, initial_capacity: int = 1024,
                 dtype="float32", device_sharding=None):
        """``device_sharding`` (a ``jax.sharding.NamedSharding`` whose
        first axis row-shards) places the device snapshot across a mesh
        instead of one device — serving mode for item matrices past one
        chip's HBM.  Capacity is always grown to a multiple of the
        device count so the leading dim splits evenly; single-row UP
        syncs use the same batched scatter as the single-device path
        (GSPMD partitions a replicated-update scatter onto the sharded
        operand with no collectives)."""
        self.features = features
        # Device snapshots lane-pad the feature dim to 128: a factor
        # tile whose minor dim is under the TPU's 128-lane width runs
        # the serving scan ~2x slower end to end (measured r05: the
        # 50-feature 20M-item phase-A kernel at 22.6 ms vs 11.6 ms with
        # the same data zero-padded to 128 lanes — the sub-width tile
        # poisons the MXU feed and every VPU op downstream).  Host
        # arrays stay at the true width; zero columns are transparent
        # to every dot-product consumer, and vtv() slices them off.
        self.device_features = features if features >= 128 else 128
        self.dtype = resolve_dtype(dtype)
        self._sharding = device_sharding
        self._cap_multiple = 1
        self._active_sharding = None
        if device_sharding is not None:
            n_dev = device_sharding.mesh.devices.size
            if n_dev & (n_dev - 1):
                raise ValueError(
                    f"sharded store needs a power-of-two device count, "
                    f"got {n_dev}")
            self._cap_multiple = n_dev
            from jax.sharding import NamedSharding, PartitionSpec
            self._active_sharding = NamedSharding(
                device_sharding.mesh,
                PartitionSpec(*device_sharding.spec[:1]))
        cap = max(16, initial_capacity, self._cap_multiple)
        if cap > _LARGE_ALIGN:
            cap = -(-cap // _LARGE_ALIGN) * _LARGE_ALIGN
        cap = -(-cap // self._cap_multiple) * self._cap_multiple
        self._id_to_row: dict[str, int] = {}
        self._row_to_id: list[str | None] = [None] * cap
        self._free: list[int] = list(range(cap - 1, -1, -1))
        self._host = np.zeros((cap, features), dtype=self.dtype)
        self._active = np.zeros(cap, dtype=bool)
        self._dirty: set[int] = set()
        self._device: jax.Array | None = None
        self._device_active: jax.Array | None = None
        self._device_version = 0
        self._recent: set[str] = set()
        self._lock = AutoReadWriteLock()
        # row->id snapshot cache for the serving hot path; invalidated
        # by bumping _mutations under the write lock
        self._mutations = 0
        self._row_ids_cache: list[str | None] | None = None
        self._row_ids_mutations = -1

    # -- basic map ops ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock.read():
            return len(self._id_to_row)

    def size(self) -> int:
        return len(self)

    def all_ids(self) -> list[str]:
        with self._lock.read():
            return list(self._id_to_row.keys())

    def __contains__(self, id_: str) -> bool:
        with self._lock.read():
            return id_ in self._id_to_row

    def get_vector(self, id_: str) -> np.ndarray | None:
        with self._lock.read():
            row = self._id_to_row.get(id_)
            return None if row is None \
                else self._host[row].astype(np.float32)

    def row_of(self, id_: str) -> int | None:
        with self._lock.read():
            return self._id_to_row.get(id_)

    def id_of(self, row: int) -> str | None:
        with self._lock.read():
            return self._row_to_id[row] if 0 <= row < len(self._row_to_id) else None

    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            row = self._id_to_row.get(id_)
            if row is None:
                if not self._free:
                    self._grow()
                row = self._free.pop()
                self._id_to_row[id_] = row
                self._row_to_id[row] = id_
                self._mutations += 1
            self._host[row] = vector
            self._active[row] = True
            self._dirty.add(row)
            self._recent.add(id_)

    def bulk_load(self, ids: list[str], matrix: np.ndarray) -> None:
        """Set many vectors at once — the fast path for MODEL publish
        consumption and benchmark model factories.  Equivalent to
        set_vector per row but one vectorized host write instead of n
        dict/array operations."""
        matrix = np.asarray(matrix)
        if matrix.dtype != self.dtype:
            matrix = matrix.astype(self.dtype)
        if matrix.shape != (len(ids), self.features):
            raise ValueError(
                f"matrix must be ({len(ids)}, {self.features}), "
                f"got {matrix.shape}")
        with self._lock.write():
            new_ids = [i for i in ids if i not in self._id_to_row]
            if len(self._free) < len(new_ids):
                # size once, exactly: a 20M-row load must not hit
                # pow2-doubling (a 33.5M-row array at 250 features is
                # 13.4 GB of pure padding)
                self._grow(len(self._id_to_row) + len(new_ids))
            rows = np.empty(len(ids), dtype=np.int64)
            for j, id_ in enumerate(ids):
                row = self._id_to_row.get(id_)
                if row is None:
                    row = self._free.pop()
                    self._id_to_row[id_] = row
                    self._row_to_id[row] = id_
                    self._mutations += 1
                rows[j] = row
            self._host[rows] = matrix
            self._active[rows] = True
            self._dirty.update(rows.tolist())
            self._recent.update(ids)

    def remove(self, id_: str) -> None:
        with self._lock.write():
            row = self._id_to_row.pop(id_, None)
            if row is not None:
                self._row_to_id[row] = None
                self._mutations += 1
                self._host[row] = 0.0
                self._active[row] = False
                self._dirty.add(row)
                self._free.append(row)

    def recent_ids(self) -> set[str]:
        """IDs set since the last retain (reference: FeatureVectors.addAllRecentTo)."""
        with self._lock.read():
            return set(self._recent)

    def retain_recent_and_ids(self, ids: Iterable[str]) -> None:
        """Drop all IDs not in ``ids`` and not recently set; clear the
        recent set (reference: FeatureVectors.retainRecentAndIDs — the
        MODEL-swap grace logic)."""
        keep = set(ids)
        with self._lock.write():
            keep |= self._recent
            for id_ in [i for i in self._id_to_row if i not in keep]:
                row = self._id_to_row.pop(id_)
                self._row_to_id[row] = None
                self._mutations += 1
                self._host[row] = 0.0
                self._active[row] = False
                self._dirty.add(row)
                self._free.append(row)
            self._recent.clear()

    def reserve(self, n_rows: int) -> None:
        """Pre-size the store for ``n_rows`` expected vectors with ONE
        exact-fit grow — the capacity ``planned_capacity`` predicts and
        the deploy-time AOT warmup compiled for.  Called at MODEL time
        with the expected-ID universe, so the per-UP-message replay
        that follows never regrows (each regrow of a multi-GB store
        re-uploads the whole device snapshot, and every intermediate
        pow2 capacity would be a compiled-shape cache miss)."""
        with self._lock.write():
            if len(self._row_to_id) < n_rows:
                self._grow(n_rows)

    def _grow(self, min_capacity: int | None = None) -> None:
        old_cap = len(self._row_to_id)
        if old_cap >= 4 * _LARGE_ALIGN:
            # large stores grow by ~12.5% in chunk steps: doubling a
            # 20M-row exact-fit array when streaming updates exhaust its
            # head-room would allocate the very padding bulk_load avoids
            new_cap = old_cap + max(_LARGE_ALIGN, old_cap // 8)
        else:
            new_cap = old_cap * 2
        if min_capacity is not None and min_capacity > new_cap:
            new_cap = min_capacity
        if new_cap > _LARGE_ALIGN:
            new_cap = -(-new_cap // _LARGE_ALIGN) * _LARGE_ALIGN
        # sharded stores: the leading dim must split evenly over the
        # mesh (exact-fit bulk_load growth can land on any size)
        m = self._cap_multiple
        if m > 1:
            new_cap = -(-new_cap // m) * m
        host = np.zeros((new_cap, self.features), dtype=self.dtype)
        host[:old_cap] = self._host
        self._host = host
        active = np.zeros(new_cap, dtype=bool)
        active[:old_cap] = self._active
        self._active = active
        self._row_to_id.extend([None] * (new_cap - old_cap))
        self._mutations += 1
        self._free.extend(range(new_cap - 1, old_cap - 1, -1))
        self._device = None  # force full re-upload at next sync
        self._device_active = None

    # -- device snapshot ----------------------------------------------------

    def device_arrays(self) -> tuple[jax.Array, jax.Array]:
        """(vectors, active_mask) on device, syncing pending host writes.

        Few dirty rows -> one batched scatter; many -> full upload.
        """
        vecs, active, _ = self.device_arrays_versioned()
        return vecs, active

    def device_arrays_versioned(self) -> tuple[jax.Array, jax.Array, int]:
        """Like device_arrays but also returns the snapshot's version,
        read atomically under the same lock — the safe cache key for
        derived device state (e.g. LSH buckets)."""
        with self._lock.write():
            cap = len(self._row_to_id)
            if self._device is None or len(self._dirty) >= cap * _FULL_UPLOAD_FRACTION:
                host = self._pad_cols(self._host)
                if self._sharding is not None:
                    self._device = jax.device_put(host, self._sharding)
                    self._device_active = jax.device_put(
                        self._active, self._active_sharding)
                else:
                    self._device = jnp.asarray(host)
                    self._device_active = jnp.asarray(self._active)
                self._device_version += 1
            elif self._dirty:
                # batched scatter of just the dirty rows; on a sharded
                # snapshot GSPMD partitions this onto the row-sharded
                # operand with replicated updates — no collectives, no
                # full re-upload (verified against the compiled HLO)
                rows = np.fromiter(self._dirty, dtype=np.int32)
                self._device = self._device.at[rows].set(
                    jnp.asarray(self._pad_cols(self._host[rows])))
                self._device_active = self._device_active.at[rows].set(
                    jnp.asarray(self._active[rows]))
                self._device_version += 1
            self._dirty.clear()
            return self._device, self._device_active, self._device_version

    @property
    def device_version(self) -> int:
        """Monotonic counter bumped on every device-snapshot change; a
        safe cache key for derived device state (unlike id() of the
        array, which CPython can reuse after free)."""
        with self._lock.read():
            return self._device_version

    def row_ids(self) -> list[str | None]:
        """Snapshot of the row -> id table for batched result decoding.
        Cached against the mutation counter: the serving hot path calls
        this once per device dispatch, and copying a 20M-entry table per
        request batch would cost more than the scoring itself."""
        with self._lock.read():
            if self._row_ids_cache is None \
                    or self._row_ids_mutations != self._mutations:
                self._row_ids_cache = list(self._row_to_id)
                self._row_ids_mutations = self._mutations
            return self._row_ids_cache

    def host_arrays(self) -> tuple[np.ndarray, np.ndarray, list[str | None]]:
        """Copy of (vectors, active, row->id) for host-side iteration."""
        with self._lock.read():
            return self._host.copy(), self._active.copy(), list(self._row_to_id)

    def _pad_cols(self, a: np.ndarray) -> np.ndarray:
        if self.device_features == self.features:
            return a
        out = np.zeros((a.shape[0], self.device_features), dtype=a.dtype)
        out[:, :self.features] = a
        return out

    def vtv(self) -> np.ndarray:
        """V^T V over live vectors — one device matmul (inactive rows are
        zero and contribute nothing; device lane-padding columns are
        zero and sliced off). Reference: FeatureVectors.getVTV."""
        vecs, _ = self.device_arrays()
        out = np.asarray(jnp.matmul(vecs.T, vecs,
                                    preferred_element_type=jnp.float32))
        return out[:self.features, :self.features]

    def map_vectors(self, fn: Callable[[str, np.ndarray], None]) -> None:
        host, active, row_ids = self.host_arrays()
        for row, id_ in enumerate(row_ids):
            if id_ is not None and active[row]:
                fn(id_, host[row].astype(np.float32))
