"""Locality-sensitive hashing for candidate pruning in top-N scoring.

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/als/model/LocalitySensitiveHash.java — hash/bits-differing
selection from target sample rate and core count (:41-124), sign-bit
hyperplane hash (:142-150), Hamming-ball candidate partitions (:156-177).

TPU-native twist: the reference partitions the item matrix by bucket and
scans selected partitions on a thread pool.  Here all items stay in one
device array alongside a precomputed bucket id per item; a query builds
its candidate set as a DEVICE-SIDE mask — popcount(bucket XOR target)
<= max_bits_differing — fused into the scoring matmul, so LSH costs one
extra elementwise op instead of a data layout.  (On TPU the brute-force
matmul often wins anyway; LSH is kept as the capability the reference
has, and for memory-partitioned deployments.)
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...common.rand import RandomManager

__all__ = ["LocalitySensitiveHash", "choose_hash_count"]

MAX_HASHES = 20


def _binom(n: int, k: int) -> int:
    return math.comb(n, k)


def choose_hash_count(sample_rate: float, num_cores: int) -> tuple[int, int]:
    """(num_hashes, max_bits_differing) achieving approximately the target
    sample rate while keeping ~num_cores partitions in play — the
    reference's selection loop (:41-75), reimplemented from its contract."""
    num_hashes = 0
    bits_differing = 0
    while num_hashes < MAX_HASHES:
        bits_differing = 0
        num_partitions_to_try = 1
        while bits_differing < num_hashes and num_partitions_to_try < num_cores:
            bits_differing += 1
            num_partitions_to_try += _binom(num_hashes, bits_differing)
        if bits_differing == num_hashes and num_partitions_to_try < num_cores:
            num_hashes += 1
            continue
        if num_partitions_to_try <= sample_rate * (1 << num_hashes):
            break
        num_hashes += 1
    return num_hashes, bits_differing


@partial(jax.jit, static_argnames=("num_hashes",))
def _bucket_kernel(vectors, hyperplanes, num_hashes: int):
    """Sign-bit bucket ids for a block of vectors: one matmul + packbits."""
    signs = jnp.matmul(vectors, hyperplanes.T,
                       preferred_element_type=jnp.float32) > 0.0
    weights = jnp.asarray([1 << i for i in range(num_hashes)], dtype=jnp.int32)
    return jnp.sum(signs.astype(jnp.int32) * weights[None, :], axis=1)


@jax.jit
def _popcount(x):
    # 32-bit popcount, classic SWAR
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return (x * 0x01010101) >> 24


class LocalitySensitiveHash:
    """Hyperplane LSH over factor vectors."""

    def __init__(self, sample_rate: float, num_features: int,
                 num_cores: int = 8):
        self.sample_rate = sample_rate
        self.num_features = num_features
        self._hp_dev: jax.Array | None = None
        self.num_hashes, self.max_bits_differing = choose_hash_count(
            sample_rate, num_cores)
        rng = RandomManager.random()
        if self.num_hashes > 0:
            # near-orthogonal hyperplanes: random Gaussian block, then QR
            # when rank allows (cleaner than the reference's random search
            # for "most orthogonal next vector"; same goal)
            g = rng.standard_normal((self.num_hashes, num_features))
            if self.num_hashes <= num_features:
                q, _ = np.linalg.qr(g.T)
                g = q.T[:self.num_hashes]
            self.hyperplanes = np.ascontiguousarray(g, dtype=np.float32)
        else:
            self.hyperplanes = np.zeros((0, num_features), dtype=np.float32)

    @property
    def num_partitions(self) -> int:
        return 1 << self.num_hashes

    def _device_hyperplanes(self) -> jax.Array:
        if self._hp_dev is None:
            self._hp_dev = jnp.asarray(self.hyperplanes)
        return self._hp_dev

    def bucket_of(self, vectors: np.ndarray) -> np.ndarray:
        """Bucket index for each row vector (reference getIndexFor :142)."""
        if self.num_hashes == 0:
            return np.zeros(len(vectors), dtype=np.int32)
        return np.asarray(self.device_buckets(jnp.asarray(vectors,
                                                          jnp.float32)))

    def device_buckets(self, vectors: jax.Array) -> jax.Array:
        """Bucket ids computed device-to-device (no host round trip; the
        input may be the serving model's whole resident item matrix)."""
        if self.num_hashes == 0:
            return jnp.zeros(vectors.shape[0], dtype=jnp.int32)
        hp = self._device_hyperplanes()
        if hp.shape[1] != vectors.shape[1]:
            # lane-padded device snapshot: zero hyperplane columns keep
            # every sign bit identical
            hp = jnp.pad(hp, [(0, 0), (0, vectors.shape[1] - hp.shape[1])])
        return _bucket_kernel(vectors, hp, self.num_hashes)

    def candidate_mask(self, query_vector: np.ndarray,
                       item_buckets: jax.Array) -> jax.Array:
        """Device-side bool mask of items within the Hamming ball of the
        query's bucket (reference getCandidateIndices :156-177 as a mask).
        Fully asynchronous: the target bucket is computed on device too,
        so building the mask never blocks on a host round trip."""
        if self.num_hashes == 0 or self.max_bits_differing >= self.num_hashes:
            return jnp.ones(item_buckets.shape, dtype=bool)
        q = jnp.asarray(np.asarray(query_vector, np.float32)[None, :])
        target = _bucket_kernel(q, self._device_hyperplanes(),
                                self.num_hashes)[0]
        diff = _popcount(jnp.bitwise_xor(item_buckets, target))
        return diff <= self.max_bits_differing

    def candidate_indices(self, query_vector: np.ndarray) -> np.ndarray:
        """All bucket ids within the Hamming ball (for partition-oriented
        callers; reference getCandidateIndices return form)."""
        target = int(self.bucket_of(query_vector[None, :])[0])
        if self.max_bits_differing >= self.num_hashes:
            return np.arange(self.num_partitions, dtype=np.int32)
        all_buckets = np.arange(self.num_partitions, dtype=np.int32)
        diff = np.bitwise_xor(all_buckets, target)
        pop = np.vectorize(lambda v: bin(v).count("1"))(diff) if len(diff) else diff
        return all_buckets[pop <= self.max_bits_differing]
