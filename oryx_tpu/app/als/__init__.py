from .rescorer import MultiRescorer, MultiRescorerProvider, Rescorer, RescorerProvider  # noqa: F401
