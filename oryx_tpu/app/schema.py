"""Input schema: config-driven feature typing shared by the k-means and
RDF app families.

Reference: app/oryx-app-common/src/main/java/com/cloudera/oryx/app/
schema/InputSchema.java:37-282 (feature names/count, id/ignored
features, numeric vs categorical, target, all<->predictor index bimap)
and CategoricalValueEncodings.java:32 (per-feature value<->index
dictionaries).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

from ..common.config import Config

__all__ = ["InputSchema", "CategoricalValueEncodings"]


class InputSchema:
    """Feature typing for learning problems needing schema information."""

    def __init__(self, config: Config):
        given_names = config.get_string_list("oryx.input-schema.feature-names")
        if not given_names:
            num = config.get_int("oryx.input-schema.num-features")
            if num <= 0:
                raise ValueError(
                    "Neither feature-names nor num-features is set")
            given_names = [str(i) for i in range(num)]
        if len(set(given_names)) != len(given_names):
            raise ValueError(f"Feature names must be unique: {given_names}")
        self.feature_names: list[str] = list(given_names)

        self.id_features = frozenset(
            config.get_string_list("oryx.input-schema.id-features"))
        ignored = frozenset(
            config.get_string_list("oryx.input-schema.ignored-features"))
        for named in (self.id_features, ignored):
            missing = named - set(self.feature_names)
            if missing:
                raise ValueError(f"Unknown features: {sorted(missing)}")

        active = set(self.feature_names) - self.id_features - ignored
        self.active_features = frozenset(active)

        numeric = config.get_optional_string_list(
            "oryx.input-schema.numeric-features")
        categorical = config.get_optional_string_list(
            "oryx.input-schema.categorical-features")
        if numeric is None:
            if categorical is None:
                raise ValueError(
                    "Neither numeric-features nor categorical-features set")
            self.categorical_features = frozenset(categorical)
            if not self.categorical_features <= self.active_features:
                raise ValueError("categorical-features must be active")
            self.numeric_features = frozenset(
                active - self.categorical_features)
        else:
            self.numeric_features = frozenset(numeric)
            if not self.numeric_features <= self.active_features:
                raise ValueError("numeric-features must be active")
            self.categorical_features = frozenset(
                active - self.numeric_features)

        self.target_feature = config.get_optional_string(
            "oryx.input-schema.target-feature")
        if self.target_feature is not None and \
                self.target_feature not in self.active_features:
            raise ValueError(
                f"Target feature is not known, an ID, or ignored: "
                f"{self.target_feature}")
        self.target_feature_index = (
            -1 if self.target_feature is None
            else self.feature_names.index(self.target_feature))

        # all-feature index <-> predictor-only index bimap
        self._feature_to_predictor: dict[int, int] = {}
        self._predictor_to_feature: dict[int, int] = {}
        p = 0
        for f in range(len(self.feature_names)):
            if self.is_active(f) and not self.is_target(f):
                self._feature_to_predictor[f] = p
                self._predictor_to_feature[p] = f
                p += 1

    # -- queries by index or name -------------------------------------------

    def _name(self, feature: int | str) -> str:
        return self.feature_names[feature] if isinstance(feature, int) \
            else feature

    @property
    def num_features(self) -> int:
        return len(self.feature_names)

    @property
    def num_predictors(self) -> int:
        return len(self._feature_to_predictor)

    def is_id(self, feature: int | str) -> bool:
        return self._name(feature) in self.id_features

    def is_active(self, feature: int | str) -> bool:
        return self._name(feature) in self.active_features

    def is_numeric(self, feature: int | str) -> bool:
        return self._name(feature) in self.numeric_features

    def is_categorical(self, feature: int | str) -> bool:
        return self._name(feature) in self.categorical_features

    def is_target(self, feature: int | str) -> bool:
        if isinstance(feature, int):
            return feature == self.target_feature_index
        return feature == self.target_feature

    def has_target(self) -> bool:
        return self.target_feature is not None

    def is_classification(self) -> bool:
        """Whether the target is categorical (reference:
        InputSchema.isClassification)."""
        return self.has_target() and self.is_categorical(self.target_feature)

    def feature_to_predictor_index(self, feature_index: int) -> int:
        return self._feature_to_predictor[feature_index]

    def predictor_to_feature_index(self, predictor_index: int) -> int:
        return self._predictor_to_feature[predictor_index]

    def __repr__(self):  # pragma: no cover
        return f"InputSchema[featureNames:{self.feature_names}]"


class CategoricalValueEncodings:
    """Per-feature dictionaries mapping category value <-> dense index
    (reference: CategoricalValueEncodings.java:32).  Input is a map of
    feature index to the feature's distinct values."""

    def __init__(self, distinct_values: Mapping[int, Iterable[str]]):
        self._encodings: dict[int, dict[str, int]] = {}
        self._decodings: dict[int, dict[int, str]] = {}
        for feature, values in distinct_values.items():
            enc: dict[str, int] = {}
            for v in values:
                if v not in enc:
                    enc[v] = len(enc)
            self._encodings[feature] = enc
            self._decodings[feature] = {i: v for v, i in enc.items()}

    def get_value_count(self, feature_index: int) -> int:
        return len(self._encodings[feature_index])

    def get_value_encoding_map(self, feature_index: int) -> dict[str, int]:
        return dict(self._encodings[feature_index])

    def get_encoding_value_map(self, feature_index: int) -> dict[int, str]:
        return dict(self._decodings[feature_index])

    def get_category_counts(self) -> dict[int, int]:
        return {f: len(m) for f, m in self._encodings.items()}

    def encode(self, feature_index: int, value: str) -> int:
        return self._encodings[feature_index][value]

    def try_encode(self, feature_index: int, value: str) -> int | None:
        """Encoding, or None for a value (or feature) with no
        dictionary entry."""
        return self._encodings.get(feature_index, {}).get(value)

    def decode(self, feature_index: int, encoding: int) -> str:
        return self._decodings[feature_index][encoding]

    @classmethod
    def from_data(cls, rows: Sequence[Sequence[str]],
                  schema: InputSchema) -> "CategoricalValueEncodings":
        """Build encodings from tokenized data for every categorical
        feature (distinct values in first-seen order, like the
        reference's distinct+collect)."""
        distinct: dict[int, list[str]] = {
            f: [] for f in range(schema.num_features)
            if schema.is_categorical(f)}
        seen: dict[int, set[str]] = {f: set() for f in distinct}
        for row in rows:
            for f, vals in distinct.items():
                v = row[f]
                if v not in seen[f]:
                    seen[f].add(v)
                    vals.append(v)
        return cls(distinct)

    def __repr__(self):  # pragma: no cover
        return f"CategoricalValueEncodings[{self.get_category_counts()}]"
