"""Process-wide fault-injection registry.

A call site declares a *named injection point*::

    faults.fire("wire-read", error=lambda: ConnectionError("injected"))

and does nothing else: with no fault registered for that name (the
production default) ``fire`` is a single module-global boolean check.
A chaos test (or ``oryx.resilience.faults.*`` config) arms the point::

    faults.inject("wire-read", mode="error", times=1)

after which the next ``times`` calls take the fault action:

========== ==========================================================
mode       effect at the call site
========== ==========================================================
``error``  raise (the point's ``error`` factory, or the spec's, or
           :class:`InjectedFault`) — a transient, retryable failure
``crash``  raise :class:`InjectedCrash` — a BaseException, so layer
           code that survives ``Exception`` dies exactly as if the
           process were killed at that line
``delay``  sleep ``delay_sec``, then continue
``hold``   park on the point's gate until :func:`release` (or a 30 s
           safety cap) — a *deterministic* stall: the test decides
           exactly which operations complete before the gate opens,
           so ordering assertions never ride on sleep margins
``drop``   return ``"drop"`` — the call site discards the operation
``duplicate`` return ``"duplicate"`` — the call site performs the
           operation twice (producer-retry duplication)
========== ==========================================================

``fired(name)`` counts consumed activations, so tests assert the fault
actually happened rather than trusting that it did.

Point names use dashes (``batch-crash-before-commit``), never dots, so
they stay addressable as single HOCON keys under
``oryx.resilience.faults``.  docs/RESILIENCE.md tables every live
point, including the serving-cluster seams (``router-shard-timeout``,
``replica-heartbeat-drop``) that drive the gateway's partial-answer
chaos tests.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable
from ..common import clock as clockmod

_log = logging.getLogger(__name__)

__all__ = ["InjectedFault", "InjectedCrash", "FaultSpec", "inject",
           "clear", "fire", "fired", "release",
           "add_fire_listener", "remove_fire_listener",
           "configure_from_config"]


class InjectedFault(Exception):
    """A transient injected failure — retryable, like the I/O error it
    stands in for."""


class InjectedCrash(BaseException):
    """A simulated process kill.  BaseException on purpose: the lambda
    layers' ``except Exception`` survival handlers must NOT absorb it,
    exactly as they could not absorb ``kill -9``."""


class FaultSpec:
    __slots__ = ("point", "mode", "remaining", "delay_sec", "error",
                 "gate")

    def __init__(self, point: str, mode: str = "error",
                 times: int | None = 1, delay_sec: float = 0.0,
                 error: Callable[[], BaseException] | None = None):
        if mode not in ("error", "crash", "delay", "hold", "drop",
                        "duplicate"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.point = point
        self.mode = mode
        self.remaining = times  # None = unlimited
        self.delay_sec = delay_sec
        self.error = error
        self.gate = threading.Event() if mode == "hold" else None


_LOCK = threading.Lock()
_SPECS: dict[str, FaultSpec] = {}
_FIRED: dict[str, int] = {}
# fast-path flag: fire() must cost one attribute read when no fault is
# armed anywhere in the process (injection points sit on hot paths)
_ACTIVE = False
# configure_from_config arms once per process (see its docstring)
_CONFIG_APPLIED = False
# observers notified on every CONSUMED activation (the flight
# recorder's "any chaos fault fired" trigger); a copy-on-write tuple
# so fire() reads it without the lock
_LISTENERS: tuple = ()


def inject(point: str, mode: str = "error", times: int | None = 1,
           delay_sec: float = 0.0,
           error: Callable[[], BaseException] | None = None) -> None:
    """Arm an injection point (last registration per point wins)."""
    global _ACTIVE
    spec = FaultSpec(point, mode=mode, times=times, delay_sec=delay_sec,
                     error=error)
    with _LOCK:
        _SPECS[point] = spec
        _ACTIVE = True
    _log.info("Fault armed: %s mode=%s times=%s", point, mode, times)


def clear(point: str | None = None) -> None:
    """Disarm one point, or every point (also resetting fired counters
    and allowing configure_from_config to arm again)."""
    global _ACTIVE, _CONFIG_APPLIED
    with _LOCK:
        if point is None:
            _SPECS.clear()
            _FIRED.clear()
            _CONFIG_APPLIED = False
        else:
            _SPECS.pop(point, None)
        _ACTIVE = bool(_SPECS)


def fired(point: str) -> int:
    """How many times the point's fault has actually been consumed."""
    with _LOCK:
        return _FIRED.get(point, 0)


def release(point: str) -> None:
    """Open a ``mode="hold"`` point's gate: every caller parked at the
    point resumes, and future activations pass straight through."""
    with _LOCK:
        spec = _SPECS.get(point)
        gate = spec.gate if spec is not None else None
    if gate is not None:
        gate.set()


def add_fire_listener(fn) -> None:
    """Register ``fn(point, mode)`` to observe every consumed fault
    activation.  Called after the spec is consumed and the registry
    lock released, BEFORE the fault's action runs — so a crash-mode
    fault is observed (and black-box captured) before it kills the
    layer.  A raising listener is swallowed: observers must never
    alter seam behavior."""
    global _LISTENERS
    with _LOCK:
        _LISTENERS = _LISTENERS + (fn,)


def remove_fire_listener(fn) -> None:
    global _LISTENERS
    with _LOCK:
        _LISTENERS = tuple(f for f in _LISTENERS if f is not fn)


def fire(point: str,
         error: Callable[[], BaseException] | None = None) -> str | None:
    """Consume one activation of ``point`` if armed.

    Returns None (no fault), or the mode string for modes the call site
    implements itself (``drop``/``duplicate``); raises for
    ``error``/``crash``; sleeps for ``delay``.  ``error`` is the call
    site's exception factory, letting the raised type match the
    transport (ConnectionError on a socket, OSError in the store...);
    a factory on the spec overrides it.
    """
    if not _ACTIVE:
        return None
    with _LOCK:
        spec = _SPECS.get(point)
        if spec is None:
            return None
        if spec.remaining is not None:
            if spec.remaining <= 0:
                return None
            spec.remaining -= 1
        _FIRED[point] = _FIRED.get(point, 0) + 1
        mode, delay = spec.mode, spec.delay_sec
        factory = spec.error or error
        gate = spec.gate
    _log.info("Fault fired: %s mode=%s", point, mode)
    for listener in _LISTENERS:
        try:
            listener(point, mode)
        except Exception:  # noqa: BLE001 — observers never alter the seam
            pass
    if mode == "delay":
        clockmod.sleep(delay)
        return None
    if mode == "hold":
        # safety cap: a test that forgets release() stalls one point
        # for 30 s, not forever
        clockmod.wait(gate, 30.0)
        return None
    if mode == "crash":
        raise InjectedCrash(f"injected crash at {point}")
    if mode == "error":
        raise factory() if factory else InjectedFault(
            f"injected fault at {point}")
    return mode  # drop / duplicate: the call site acts


def configure_from_config(config) -> None:
    """Arm every fault declared under ``oryx.resilience.faults``.

    Each child is a point name mapping to ``{mode, times, delay-ms}``
    (``times`` null/absent = 1; ``times = -1`` = unlimited).  An empty
    ``faults`` block — the shipped default — arms nothing and costs
    nothing.  Layers call this at construction, so a config file alone
    can stage a chaos run with no test code.

    Arms at most ONCE per process (until :func:`clear`): a supervised
    restart reconstructs the layer, and re-arming a finite-``times``
    crash fault on every incarnation would crash each rebuilt layer at
    the same seam until the restart budget dies — the opposite of what
    a staged one-shot fault means.
    """
    global _CONFIG_APPLIED
    try:
        node = config.get("oryx.resilience.faults")
    except KeyError:
        return
    if not isinstance(node, dict) or not node:
        return
    with _LOCK:
        if _CONFIG_APPLIED:
            return
        _CONFIG_APPLIED = True
    for point, spec in node.items():
        if not isinstance(spec, dict):
            continue
        times = spec.get("times", 1)
        inject(point,
               mode=str(spec.get("mode", "error")),
               times=None if times in (None, -1) else int(times),
               delay_sec=float(spec.get("delay-ms", 0)) / 1000.0)
