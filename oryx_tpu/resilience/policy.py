"""Resilience policies: retry/backoff, deadlines, circuit breaker,
supervised restart.

These are the generic combinators the runtime threads through its
broker I/O, storage, serving and layer-lifecycle seams; the
fault-injection registry (:mod:`.faults`) exists to prove each of them
under the failure it guards against (tests/test_resilience_it.py).

Every named :class:`Retry` and :class:`CircuitBreaker` self-registers
in a process-wide table; :func:`resilience_snapshot` renders their
counters for every tier's ``/metrics`` surface — the serving tier and
router on their main port, the headless tiers (speed, batch, mirror)
via the side-door ObsServer (obs/server.py).
"""

from __future__ import annotations

import logging
import random
import threading
import weakref
from typing import Any, Callable

from ..common import clock as clockmod
from .faults import InjectedFault

_log = logging.getLogger(__name__)

__all__ = [
    "DeadlineExceeded", "CircuitOpenError", "Deadline", "Backoff",
    "Retry", "CircuitBreaker", "Supervisor", "ResilientTopicProducer",
    "resilience_snapshot", "run_with_resubscribe",
]


def run_with_resubscribe(fn: Callable[[], Any], stop: "threading.Event",
                         what: str, backoff: "Backoff | None" = None,
                         log: logging.Logger | None = None,
                         healthy_reset_sec: float = 300.0,
                         clock: Callable[[], float] = clockmod.monotonic
                         ) -> None:
    """Run a blocking subscription (``fn`` returns only on clean end)
    until it completes or ``stop`` is set, restarting it with backoff
    on failure.

    The shared shape of the speed/serving update-topic consumers: a
    broker failure mid-tail must not freeze model state for the life of
    the process, and since their state build is a full replay from
    offset 0, recovery IS the cold-start path — the same proven code.

    Two bounds matter for failover latency (a mirror or router being
    re-pointed must neither wait out a stale backoff nor a full one):

    - a subscription that stayed up ``healthy_reset_sec`` before
      failing resets the attempt counter, so the NEXT resubscribe
      waits the initial backoff, not the lifetime-accumulated maximum
      (the Supervisor's healthy-reset contract, applied here);
    - the inter-attempt sleep is ``stop.wait`` — setting ``stop``
      interrupts it immediately, so shutdown latency is bounded by the
      running ``fn``, never by a backoff sleep."""
    backoff = backoff or Backoff(initial=0.1, maximum=5.0)
    log = log or _log
    attempt = 0
    while not stop.is_set():
        started = clock()
        try:
            fn()
            return  # clean end: stop was requested
        except Exception:  # noqa: BLE001 — resubscribe, don't die
            if clock() - started >= healthy_reset_sec:
                attempt = 0
            attempt += 1
            log.exception("%s failed; resubscribing (attempt %d)",
                          what, attempt)
            clockmod.wait(stop, backoff.delay(attempt))


class DeadlineExceeded(Exception):
    """A per-call deadline expired before the work completed (mapped to
    HTTP 503 at the serving surface)."""


class CircuitOpenError(Exception):
    """Fast-fail: the guarded dependency is presumed down and the
    breaker is shedding calls instead of queueing them."""


# -- named-instance registry (the /metrics feed) -----------------------------

_REGISTRY: "weakref.WeakValueDictionary[str, Any]" = \
    weakref.WeakValueDictionary()
_REGISTRY_LOCK = threading.Lock()


def _register(name: str, instance) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY[name] = instance


def resilience_snapshot() -> dict:
    """{name: stats} for every live named Retry / CircuitBreaker."""
    with _REGISTRY_LOCK:
        items = list(_REGISTRY.items())
    return {name: inst.stats() for name, inst in sorted(items)}


# -- deadlines ---------------------------------------------------------------

class Deadline:
    """A monotonic-clock deadline carried from the serving front end
    down through the request micro-batcher: work that cannot finish in
    time is refused up front (503) instead of queueing to die."""

    __slots__ = ("t_end",)

    def __init__(self, t_end: float):
        self.t_end = t_end

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(clockmod.monotonic() + seconds)

    @property
    def expired(self) -> bool:
        return clockmod.monotonic() >= self.t_end

    def remaining(self) -> float:
        return max(0.0, self.t_end - clockmod.monotonic())

    def check(self, what: str = "call") -> None:
        if self.expired:
            raise DeadlineExceeded(f"deadline exceeded in {what}")

    @staticmethod
    def tightest(*deadlines: "Deadline | None") -> "Deadline | None":
        live = [d for d in deadlines if d is not None]
        if not live:
            return None
        return min(live, key=lambda d: d.t_end)


# -- backoff -----------------------------------------------------------------

class Backoff:
    """Exponential backoff with full jitter and a cap.

    ``delay(attempt)`` for attempt 1, 2, ... — deterministic when
    ``jitter=0`` (chaos tests pin it to assert schedules)."""

    __slots__ = ("initial", "maximum", "multiplier", "jitter", "_rng")

    def __init__(self, initial: float = 0.05, maximum: float = 2.0,
                 multiplier: float = 2.0, jitter: float = 0.2,
                 rng: random.Random | None = None):
        self.initial = initial
        self.maximum = maximum
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        base = min(self.maximum,
                   self.initial * self.multiplier ** max(0, attempt - 1))
        if not self.jitter:
            return base
        # full jitter on the top `jitter` fraction: retries from many
        # threads decorrelate instead of thundering back together
        return base * (1.0 - self.jitter * self._rng.random())

    @classmethod
    def from_config(cls, config, path: str = "oryx.resilience.retry"
                    ) -> "Backoff":
        return cls(
            initial=config.get_int(f"{path}.initial-backoff-ms") / 1000.0,
            maximum=config.get_int(f"{path}.max-backoff-ms") / 1000.0,
            multiplier=config.get_double(f"{path}.multiplier"),
            jitter=config.get_double(f"{path}.jitter"))


# -- retry -------------------------------------------------------------------

class Retry:
    """Bounded retry of transient failures with backoff.

    ``retryable`` is an exception tuple or a predicate; anything else
    propagates immediately.  An optional :class:`Deadline` bounds the
    whole call including sleeps — on expiry the last failure is
    re-raised rather than swallowed into a DeadlineExceeded."""

    def __init__(self, name: str,
                 retryable: tuple | Callable[[BaseException], bool]
                 = (ConnectionError, OSError, TimeoutError,
                    InjectedFault),
                 max_attempts: int = 5,
                 backoff: Backoff | None = None,
                 sleep: Callable[[float], None] = clockmod.sleep):
        self.name = name
        self._retryable = retryable
        self.max_attempts = max(1, max_attempts)
        self.backoff = backoff or Backoff()
        self._sleep = sleep
        self._lock = threading.Lock()
        self.calls = 0
        self.retries = 0
        self.give_ups = 0
        _register(name, self)

    @classmethod
    def from_config(cls, name: str, config, retryable=None) -> "Retry":
        kw = {} if retryable is None else {"retryable": retryable}
        return cls(name,
                   max_attempts=config.get_int(
                       "oryx.resilience.retry.max-attempts"),
                   backoff=Backoff.from_config(config), **kw)

    def _is_retryable(self, e: BaseException) -> bool:
        r = self._retryable
        # exception classes are callable too: a bare `retryable=OSError`
        # must mean isinstance, not predicate (calling it would build an
        # exception object — truthy for EVERY error)
        if isinstance(r, tuple) or (isinstance(r, type)
                                    and issubclass(r, BaseException)):
            return isinstance(e, r)
        return bool(r(e))

    def call(self, fn: Callable, *args,
             deadline: Deadline | None = None, **kwargs):
        with self._lock:
            self.calls += 1
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classified below
                if not self._is_retryable(e) or attempt >= self.max_attempts:
                    with self._lock:
                        self.give_ups += 1
                    raise
                pause = self.backoff.delay(attempt)
                if deadline is not None \
                        and deadline.remaining() <= pause:
                    with self._lock:
                        self.give_ups += 1
                    raise  # no time left to retry: surface the cause
                with self._lock:
                    self.retries += 1
                _log.debug("%s: retrying after %s (attempt %d/%d)",
                           self.name, e, attempt, self.max_attempts)
                self._sleep(pause)

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def stats(self) -> dict:
        with self._lock:
            return {"kind": "retry", "calls": self.calls,
                    "retries": self.retries, "give_ups": self.give_ups,
                    "max_attempts": self.max_attempts}


# -- circuit breaker ---------------------------------------------------------

class CircuitBreaker:
    """Closed -> open after ``failure_threshold`` consecutive failures;
    open sheds calls (CircuitOpenError) for ``reset_timeout_sec``; then
    half-open admits ``half_open_probes`` probe calls — success closes,
    failure re-opens.  ``clock`` is injectable so chaos tests control
    time instead of sleeping through it."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_sec: float = 1.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = clockmod.monotonic):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_sec = reset_timeout_sec
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.opens = 0
        self.rejected = 0
        self.calls = 0
        _register(name, self)

    @classmethod
    def from_config(cls, name: str, config,
                    path: str = "oryx.resilience.breaker"
                    ) -> "CircuitBreaker":
        return cls(
            name,
            failure_threshold=config.get_int(f"{path}.failure-threshold"),
            reset_timeout_sec=config.get_int(
                f"{path}.reset-timeout-ms") / 1000.0,
            half_open_probes=config.get_int(f"{path}.half-open-probes"))

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _admit(self) -> bool:
        """Reserve the right to make one call; False = shed it."""
        with self._lock:
            self.calls += 1
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if (self._clock() - self._opened_at
                        < self.reset_timeout_sec):
                    self.rejected += 1
                    return False
                self._state = self.HALF_OPEN
                self._probes_in_flight = 0
            # half-open: admit a bounded number of concurrent probes
            if self._probes_in_flight >= self.half_open_probes:
                self.rejected += 1
                return False
            self._probes_in_flight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == self.HALF_OPEN:
                self._state = self.CLOSED
                _log.info("%s: circuit closed (probe succeeded)",
                          self.name)
            self._probes_in_flight = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN \
                    or self._failures >= self.failure_threshold:
                if self._state != self.OPEN:
                    self.opens += 1
                    _log.warning("%s: circuit OPEN after %d failure(s)",
                                 self.name, self._failures)
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probes_in_flight = 0

    def call(self, fn: Callable, *args, **kwargs):
        if not self._admit():
            raise CircuitOpenError(
                f"{self.name}: circuit open, call shed")
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            # BaseException included: an InjectedCrash (or thread kill)
            # during a half-open probe must release the probe slot, or
            # _probes_in_flight stays pinned and the breaker sheds
            # every later call forever
            self.record_failure()
            raise
        self.record_success()
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"kind": "breaker", "state": self._state,
                    "consecutive_failures": self._failures,
                    "opens": self.opens, "rejected": self.rejected,
                    "calls": self.calls}


# -- supervised restart ------------------------------------------------------

class Supervisor:
    """Restart-with-backoff around a layer's start/await_/close
    lifecycle (deploy/main.py).

    The layers' worker threads deliberately survive ``Exception`` but
    die on anything harsher (an injected crash, a real bug escaping the
    survival handlers); ``await_`` returning while ``close`` was never
    requested IS the crash signal.  The supervisor rebuilds the layer
    from its factory and restarts, with backoff, up to
    ``max_restarts`` times."""

    def __init__(self, factory: Callable[[], Any], name: str = "layer",
                 max_restarts: int = 5, backoff: Backoff | None = None,
                 sleep: Callable[[float], None] = clockmod.sleep,
                 healthy_reset_sec: float = 300.0,
                 clock: Callable[[], float] = clockmod.monotonic):
        self.factory = factory
        self.name = name
        self.max_restarts = max_restarts
        self.backoff = backoff or Backoff(initial=0.2, maximum=5.0)
        self._sleep = sleep
        self._stop = threading.Event()
        self.restarts = 0
        self.layer = None
        # a layer that stayed up this long earns its restart budget
        # back: the cap bounds crash LOOPS, not lifetime crash count
        self.healthy_reset_sec = healthy_reset_sec
        self._clock = clock

    @classmethod
    def from_config(cls, factory, name: str, config) -> "Supervisor":
        path = "oryx.resilience.supervisor"
        return cls(factory, name=name,
                   max_restarts=config.get_int(f"{path}.max-restarts"),
                   backoff=Backoff(
                       initial=config.get_int(
                           f"{path}.initial-backoff-ms") / 1000.0,
                       maximum=config.get_int(
                           f"{path}.max-backoff-ms") / 1000.0))

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        """Blocks until the layer exits cleanly (stop requested /
        KeyboardInterrupt) or the restart budget is exhausted."""
        while not self._stop.is_set():
            started = self._clock()
            self.layer = None  # a failed factory() must not re-close
            try:               # the previous, already-closed layer
                # factory()/start() are INSIDE the try: a rebuild
                # against a still-down dependency (broker gone, port
                # not yet released) must count as a crash and retry
                # with backoff, not kill the process
                self.layer = self.factory()
                self.layer.start()
                self.layer.await_()
            except KeyboardInterrupt:
                self._stop.set()
            except Exception:  # noqa: BLE001 — a failed (re)build is a
                _log.exception("%s: layer failed", self.name)  # crash
            finally:
                if self.layer is not None:
                    try:
                        self.layer.close()
                    except Exception:  # noqa: BLE001 — best-effort
                        _log.exception("%s: close() failed", self.name)
            if self._stop.is_set():
                return
            if self._clock() - started >= self.healthy_reset_sec:
                self.restarts = 0
            if self.restarts >= self.max_restarts:
                _log.error("%s: gave up after %d restart(s)", self.name,
                           self.restarts)
                raise RuntimeError(
                    f"{self.name}: exceeded {self.max_restarts} restarts")
            self.restarts += 1
            pause = self.backoff.delay(self.restarts)
            _log.warning("%s: layer died; restart %d/%d in %.2fs",
                         self.name, self.restarts, self.max_restarts,
                         pause)
            self._sleep(pause)


# -- producer wrapper --------------------------------------------------------

class ResilientTopicProducer:
    """Retry + circuit breaker around any TopicProducer.

    Breaker outside retry: one exhausted retry sequence counts as ONE
    breaker failure, so the threshold measures sustained outage, not
    attempt noise.  With the breaker open, sends shed immediately
    (CircuitOpenError) — the serving tier maps that to 503 and the
    half-open probe restores service without a restart."""

    def __init__(self, inner, retry: Retry,
                 breaker: CircuitBreaker | None = None):
        self._inner = inner
        self._retry = retry
        self._breaker = breaker

    def send(self, key: str | None, message: str,
             headers: dict | None = None) -> None:
        # keyword pass-through only when present keeps wrapped
        # producers whose send is (key, message)-only working untouched
        kw = {} if headers is None else {"headers": headers}
        if self._breaker is None:
            self._retry.call(self._inner.send, key, message, **kw)
        else:
            self._breaker.call(self._retry.call, self._inner.send,
                               key, message, **kw)

    def send_many(self, entries: list[tuple[str | None, str,
                                            dict | None]]) -> None:
        """Pipelined multi-record send under ONE retry/breaker
        admission: the whole batch is one logical produce, so a
        mid-batch failure retries the batch (at-least-once — the
        update-topic SET semantics and the speed checkpoint's dedup
        scan absorb the duplicates).  Falls back to a per-record loop
        for wrapped producers without ``send_many``."""
        entries = list(entries)
        if not entries:
            return
        send_many = getattr(self._inner, "send_many", None)
        if send_many is not None:
            fn, args = send_many, (entries,)
        else:
            fn, args = self._send_each, (entries,)
        if self._breaker is None:
            self._retry.call(fn, *args)
        else:
            self._breaker.call(self._retry.call, fn, *args)

    def _send_each(self, entries) -> None:
        for key, message, headers in entries:
            kw = {} if headers is None else {"headers": headers}
            self._inner.send(key, message, **kw)

    def get_update_broker(self) -> str:
        return self._inner.get_update_broker()

    def get_topic(self) -> str:
        return self._inner.get_topic()

    def close(self) -> None:
        self._inner.close()
