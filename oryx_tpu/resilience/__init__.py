"""Resilience layer: fault injection + retry/deadline/breaker policies.

Two halves, designed together so the second can be *proven* by the
first (the lineage-recovery argument: recovery code that has never run
under an injected failure is a claim, not a property — PAPERS.md, the
Spark Streaming lineage papers; Kafka delivery-semantics design notes):

- :mod:`.faults` — a process-wide registry of named injection points
  threaded through the kafka transport, the lambda layers, and the
  artifact store.  Disabled (the default) it is one dict-free boolean
  check per call site; enabled (programmatically in chaos tests, or via
  ``oryx.resilience.faults.*`` config) it raises, delays, duplicates
  or crashes at exactly the seam under test.

- :mod:`.policy` — the generic resilience combinators the runtime uses
  at those same seams: ``Retry`` (exponential backoff + jitter +
  deadline), ``Deadline`` propagation from the serving front end into
  the request micro-batcher, a ``CircuitBreaker`` with half-open
  probing around broker I/O, and a ``Supervisor`` that restarts crashed
  layer threads with backoff (deploy/main.py).

Every named policy instance registers itself; ``resilience_snapshot()``
feeds the serving ``/metrics`` surface.
"""

from .faults import (FaultSpec, InjectedCrash, InjectedFault,
                     clear as clear_faults, configure_from_config,
                     fire, fired, inject)
from .policy import (Backoff, CircuitBreaker, CircuitOpenError, Deadline,
                     DeadlineExceeded, ResilientTopicProducer, Retry,
                     Supervisor, resilience_snapshot)

__all__ = [
    "FaultSpec", "InjectedCrash", "InjectedFault", "inject", "fire",
    "fired", "clear_faults", "configure_from_config",
    "Backoff", "CircuitBreaker", "CircuitOpenError", "Deadline",
    "DeadlineExceeded", "ResilientTopicProducer", "Retry", "Supervisor",
    "resilience_snapshot",
]
