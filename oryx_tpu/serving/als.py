"""ALS serving REST resources.

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/als/ — Recommend.java:74-113, RecommendToMany.java:57,
RecommendToAnonymous.java:59, RecommendWithContext.java:59,
Similarity.java:60, SimilarityToItem.java:44, Estimate.java:51,
EstimateForAnonymous.java:48 (buildTemporaryUserVector :74-96),
Because.java:52, KnownItems.java:35, MostActiveUsers.java:47,
MostPopularItems.java:52, MostSurprising.java:54,
PopularRepresentativeItems.java:43, AllUserIDs/AllItemIDs.java:34,
Preference.java:42-76, Ingest.java:61, DTOs IDValue/IDCount.

howMany/offset behavior follows Recommend: compute howMany+offset
results, return the slice [offset, offset+howMany).
"""

from __future__ import annotations

import dataclasses
import gzip
import io
import json
import math
import zipfile

import numpy as np

from ..api.serving import OryxServingException
from ..app.als.serving_model import ALSServingModel
from ..common import text as text_utils
from ..lambda_rt.http import Request, Route
from ..ops import als_fold_in
from . import console
from .framework import get_serving_model, send_input, send_input_many

# IDValue/IDCount and the param/path parsing helpers are also the
# cluster gateway's vocabulary (cluster/router.py re-serves this
# surface via scatter-gather): exported so that reuse is a contract,
# not a reach into private names
__all__ = ["ROUTES", "IDValue", "IDCount", "parse_id_value_segments",
           "how_many_offset"]


@dataclasses.dataclass
class IDValue:
    """Response DTO (reference: IDValue.java:21, HasCSV)."""

    id: str
    value: float

    def to_csv(self) -> str:
        return f"{self.id},{self.value}"

    def to_json_fragment(self) -> str:
        # hand-built: the hot /recommend path serializes thousands of
        # these per second and json.dumps' default-callback protocol
        # costs ~3x (json.encoder C-escapes the id; float repr IS the
        # JSON float form for finite scores; non-finite scores keep
        # json.dumps' spelling, which repr would break)
        v = float(self.value)
        if not math.isfinite(v):
            return json.dumps({"id": self.id, "value": v},
                              separators=(",", ":"))
        return f'{{"id":{json.dumps(self.id)},"value":{v!r}}}'


@dataclasses.dataclass
class IDCount:
    """Response DTO (reference: IDCount.java, HasCSV)."""

    id: str
    count: int

    def to_csv(self) -> str:
        return f"{self.id},{self.count}"

    def to_json_fragment(self) -> str:
        return f'{{"id":{json.dumps(self.id)},"count":{int(self.count)}}}'


def _als_model(req: Request) -> ALSServingModel:
    model = get_serving_model(req)
    if not isinstance(model, ALSServingModel):
        raise OryxServingException(503, "Model not available yet")
    return model


def _how_many_offset(req: Request) -> tuple[int, int]:
    how_many = req.q_int("howMany", 10)
    offset = req.q_int("offset", 0)
    if how_many <= 0:
        raise OryxServingException(400, "howMany must be positive")
    if offset < 0:
        raise OryxServingException(400, "offset must be non-negative")
    return how_many, offset


def _slice(pairs: list[tuple[str, float]], how_many: int,
           offset: int) -> list[IDValue]:
    return [IDValue(i, v) for i, v in pairs[offset:offset + how_many]]


def _check_exists(cond: bool, what: str) -> None:
    if not cond:
        raise OryxServingException(404, what)


# public aliases of the parsing helpers (the gateway's imports)
def how_many_offset(req: Request) -> tuple[int, int]:
    return _how_many_offset(req)


def parse_id_value_segments(raw: str) -> list[tuple[str, float]]:
    return _parse_id_value_segments(raw)


def _parse_id_value_segments(raw: str) -> list[tuple[str, float]]:
    """Path tail ``i1=2.5/i2/i3=0.5`` -> [(id, strength)] with default 1.0
    (reference: EstimateForAnonymous.parsePathSegments)."""
    out = []
    for seg in raw.split("/"):
        if "=" in seg:
            id_, val = seg.split("=", 1)
            out.append((id_, float(val)))
        else:
            out.append((seg, 1.0))
    return out


def _build_temporary_user_vector(model: ALSServingModel,
                                 item_values: list[tuple[str, float]],
                                 xu: np.ndarray | None) -> np.ndarray | None:
    """Sequentially fold context items into a (possibly absent) user
    vector (reference: EstimateForAnonymous.buildTemporaryUserVector).
    The whole ordered context is one lax.scan device dispatch
    (ops.als_fold_in.fold_in_sequential) instead of a per-item
    round-trip."""
    solver = model.get_yty_solver(blocking=True)
    if solver is None:
        raise OryxServingException(503, "No solver available for model yet")
    return als_fold_in.fold_in_sequential(
        solver, list(item_values), model.get_item_vector, xu,
        model.implicit, model.features)


def _rescorer(model: ALSServingModel, hook: str, req: Request, *args):
    provider = model.rescorer_provider
    if provider is None:
        return None
    return getattr(provider, hook)(*args, req.q_list("rescorerParams"))


def _dot_top_n(req: Request, model: ALSServingModel, how_many: int,
               user_vector: np.ndarray, exclude: set[str],
               rescorer) -> list[tuple[str, float]]:
    """Dot-product top-N, coalesced with concurrent requests through the
    app-scope TopNBatcher unless a rescorer plugin forces the exact
    single-request path.  LSH-configured models batch too: per-query
    Hamming-ball masks are fused into the shared dispatch
    (ALSServingModel.top_n_batch)."""
    batcher = req.context.get("top_n_batcher")
    if batcher is not None and rescorer is None:
        # the front-end deadline rides into the batcher queue: expired
        # work is shed as 503 instead of occupying a device dispatch
        return batcher.top_n(model, how_many, user_vector, exclude,
                             deadline=req.deadline)
    if req.deadline is not None:
        req.deadline.check("top_n")
    return model.top_n(how_many, user_vector=user_vector, exclude=exclude,
                       rescorer=rescorer)


# -- recommend ---------------------------------------------------------------

def _recommend(req: Request):
    model = _als_model(req)
    user_id = req.params["userID"]
    how_many, offset = _how_many_offset(req)
    consider_known = (req.q1("considerKnownItems", "false") == "true")
    user_vector = model.get_user_vector(user_id)
    _check_exists(user_vector is not None, user_id)
    exclude = set() if consider_known else model.get_known_items(user_id)
    rescorer = _rescorer(model, "get_recommend_rescorer", req, user_id)
    pairs = _dot_top_n(req, model, how_many + offset, user_vector,
                       exclude, rescorer)
    return _slice(pairs, how_many, offset)


def _recommend_to_many(req: Request):
    model = _als_model(req)
    user_ids = req.params["userIDs"].split("/")
    how_many, offset = _how_many_offset(req)
    consider_known = (req.q1("considerKnownItems", "false") == "true")
    vectors, exclude = [], set()
    for uid in user_ids:
        v = model.get_user_vector(uid)
        if v is not None:
            vectors.append(v)
            if not consider_known:
                exclude |= model.get_known_items(uid)
    _check_exists(bool(vectors), str(user_ids))
    mean_vector = np.mean(vectors, axis=0)
    rescorer = _rescorer(model, "get_recommend_rescorer", req, user_ids[0])
    pairs = _dot_top_n(req, model, how_many + offset, mean_vector,
                       exclude, rescorer)
    return _slice(pairs, how_many, offset)


def _recommend_to_anonymous(req: Request):
    model = _als_model(req)
    item_values = _parse_id_value_segments(req.params["itemIDs"])
    how_many, offset = _how_many_offset(req)
    xu = _build_temporary_user_vector(model, item_values, None)
    _check_exists(xu is not None, req.params["itemIDs"])
    known = {i for i, _ in item_values}
    rescorer = _rescorer(model, "get_recommend_to_anonymous_rescorer", req,
                         sorted(known))
    pairs = _dot_top_n(req, model, how_many + offset, xu, known, rescorer)
    return _slice(pairs, how_many, offset)


def _recommend_with_context(req: Request):
    model = _als_model(req)
    user_id = req.params["userID"]
    item_values = _parse_id_value_segments(req.params["itemIDs"])
    how_many, offset = _how_many_offset(req)
    xu = model.get_user_vector(user_id)
    _check_exists(xu is not None, user_id)
    xu = _build_temporary_user_vector(model, item_values, xu)
    exclude = model.get_known_items(user_id) | {i for i, _ in item_values}
    rescorer = _rescorer(model, "get_recommend_rescorer", req, user_id)
    pairs = _dot_top_n(req, model, how_many + offset, xu, exclude, rescorer)
    return _slice(pairs, how_many, offset)


# -- similarity --------------------------------------------------------------

def _similarity(req: Request):
    model = _als_model(req)
    item_ids = req.params["itemIDs"].split("/")
    how_many, offset = _how_many_offset(req)
    vectors = []
    for iid in item_ids:
        v = model.get_item_vector(iid)
        _check_exists(v is not None, iid)
        vectors.append(v)
    rescorer = _rescorer(model, "get_most_similar_items_rescorer", req)
    pairs = model.top_n(how_many + offset,
                        cosine_to=np.stack(vectors, axis=1),
                        exclude=set(item_ids), rescorer=rescorer)
    return _slice(pairs, how_many, offset)


def _similarity_to_item(req: Request):
    model = _als_model(req)
    to_item = req.params["toItemID"]
    item_ids = req.params["itemIDs"].split("/")
    to_vec = model.get_item_vector(to_item)
    _check_exists(to_vec is not None, to_item)
    to_norm = float(np.linalg.norm(to_vec))
    out = []
    for iid in item_ids:
        v = model.get_item_vector(iid)
        _check_exists(v is not None, iid)
        denom = to_norm * float(np.linalg.norm(v))
        out.append(IDValue(iid, float(np.dot(v, to_vec)) / denom
                           if denom > 0 else 0.0))
    return out


# -- estimates ---------------------------------------------------------------

def _estimate(req: Request):
    model = _als_model(req)
    user_id = req.params["userID"]
    item_ids = req.params["itemIDs"].split("/")
    xu = model.get_user_vector(user_id)
    _check_exists(xu is not None, user_id)
    out = []
    for iid in item_ids:
        yi = model.get_item_vector(iid)
        out.append(IDValue(iid, 0.0 if yi is None else float(xu @ yi)))
    return out


def _estimate_for_anonymous(req: Request):
    model = _als_model(req)
    to_item = req.params["toItemID"]
    to_vec = model.get_item_vector(to_item)
    _check_exists(to_vec is not None, to_item)
    item_values = _parse_id_value_segments(req.params["itemIDs"])
    xu = _build_temporary_user_vector(model, item_values, None)
    return 0.0 if xu is None else float(np.dot(xu, to_vec))


def _because(req: Request):
    model = _als_model(req)
    user_id = req.params["userID"]
    item_id = req.params["itemID"]
    how_many, offset = _how_many_offset(req)
    item_vector = model.get_item_vector(item_id)
    _check_exists(item_vector is not None, item_id)
    known = model.get_known_items(user_id)
    if not known:
        return []
    norm = float(np.linalg.norm(item_vector))
    sims = []
    for other in known:
        ov = model.get_item_vector(other)
        if ov is None:
            continue
        denom = norm * float(np.linalg.norm(ov))
        sims.append((other, float(np.dot(ov, item_vector)) / denom
                     if denom > 0 else 0.0))
    sims.sort(key=lambda t: -t[1])
    return _slice(sims, how_many, offset)


def _most_surprising(req: Request):
    model = _als_model(req)
    user_id = req.params["userID"]
    how_many, offset = _how_many_offset(req)
    xu = model.get_user_vector(user_id)
    _check_exists(xu is not None, user_id)
    known = model.get_known_items(user_id)
    if not known:
        return []
    dots = []
    for iid in known:
        yi = model.get_item_vector(iid)
        if yi is not None:
            dots.append((iid, float(xu @ yi)))
    dots.sort(key=lambda t: t[1])  # ascending: most surprising first
    return _slice(dots, how_many, offset)


# -- popularity / enumeration ------------------------------------------------

def _most_active_users(req: Request):
    model = _als_model(req)
    how_many, offset = _how_many_offset(req)
    rescorer = _rescorer(model, "get_most_active_users_rescorer", req)
    counts = sorted(model.get_known_item_counts().items(),
                    key=lambda t: -t[1])
    out = []
    for uid, c in counts:
        if rescorer is not None and rescorer.is_filtered(uid):
            continue
        out.append((uid, c))
    return [IDCount(i, int(c)) for i, c in out[offset:offset + how_many]]


def _most_popular_items(req: Request):
    model = _als_model(req)
    how_many, offset = _how_many_offset(req)
    rescorer = _rescorer(model, "get_most_popular_items_rescorer", req)
    ranked = sorted(model.get_item_popularity_counts().items(),
                    key=lambda t: -t[1])
    out = []
    for iid, c in ranked:
        if rescorer is not None and rescorer.is_filtered(iid):
            continue
        out.append((iid, c))
    return [IDCount(i, int(c)) for i, c in out[offset:offset + how_many]]


def _popular_representative_items(req: Request):
    """Top item along each latent feature axis
    (reference: PopularRepresentativeItems.java:43-60)."""
    model = _als_model(req)
    items = []
    for i in range(model.features):
        unit = np.zeros(model.features, dtype=np.float32)
        unit[i] = 1.0
        top = model.top_n(1, user_vector=unit)
        items.append(top[0][0] if top else None)
    return items


def _all_user_ids(req: Request):
    return _als_model(req).all_user_ids()


def _all_item_ids(req: Request):
    return _als_model(req).all_item_ids()


def _known_items(req: Request):
    model = _als_model(req)
    return sorted(model.get_known_items(req.params["userID"]))


# -- write path --------------------------------------------------------------

def _pref_post(req: Request):
    _als_model(req)  # 503 gate
    user_id, item_id = req.params["userID"], req.params["itemID"]
    body = req.body.decode().strip()
    value = body if body else "1"
    float(value)  # validate
    send_input(req, f"{user_id},{item_id},{value}")
    return None


def _pref_delete(req: Request):
    _als_model(req)
    user_id, item_id = req.params["userID"], req.params["itemID"]
    # empty strength means 'delete' on the wire
    send_input(req, f"{user_id},{item_id},")
    return None


def _decode_ingest_payload(data: bytes, ctype: str, filename: str) -> str:
    """One uploaded payload -> text, sniffing gzip/zip from the content
    type or filename (reference: Ingest.java maybeDecompress by part
    content type and file extension)."""
    if "gzip" in ctype or filename.endswith(".gz"):
        try:
            return gzip.decompress(data).decode()
        except gzip.BadGzipFile:
            # transport layer may have already decoded Content-Encoding
            return data.decode()
    if "zip" in ctype or filename.endswith(".zip"):
        texts = []
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            for name in zf.namelist():
                texts.append(zf.read(name).decode())
        return "\n".join(texts)
    return data.decode()


def _multipart_texts(body: bytes, ctype: str) -> list[str]:
    """Decode every file part of a multipart/form-data body, each part
    independently gzip/zip-sniffed (reference: Ingest.java:61-... via
    the servlet fileupload parser)."""
    import email
    import email.policy

    msg = email.message_from_bytes(
        b"Content-Type: " + ctype.encode("utf-8") + b"\r\n\r\n" + body,
        policy=email.policy.default)
    if not msg.is_multipart():
        raise OryxServingException(400, "bad multipart body")
    texts = []
    for part in msg.iter_parts():
        data = part.get_payload(decode=True)
        if data is None:
            continue
        texts.append(_decode_ingest_payload(
            data, part.get_content_type(), part.get_filename() or ""))
    if not texts:
        raise OryxServingException(400, "no file parts in multipart body")
    return texts


def _ingest(req: Request):
    """Bulk CSV ingest; accepts plain, gzip, or zip bodies, and
    multipart/form-data uploads whose parts are each plain/gzip/zip
    (reference: Ingest.java:61-...)."""
    body = req.body
    ctype = req.headers.get("Content-Type", "")
    encoding = req.headers.get("Content-Encoding", "")
    if ctype.startswith("multipart/form-data"):
        text = "\n".join(_multipart_texts(body, ctype))
    else:
        # content type OR transfer encoding may declare the compression
        text = _decode_ingest_payload(body, f"{ctype} {encoding}", "")
    # validate the whole (already fully buffered) body before sending
    # anything, so a bad line can't leave a partial ingest behind
    lines = [ln.strip() for ln in text.splitlines() if ln.strip()]
    for line in lines:
        fields = text_utils.parse_input_line(line)
        if not 2 <= len(fields) <= 4:
            raise OryxServingException(400, f"bad line: {line}")
    # one pipelined produce for the whole body (kafka send_many): a
    # 200 means EVERY line is durable in the input topic
    if lines:
        send_input_many(req, lines)
    return {"ingested": len(lines)}


ROUTES = [
    Route("GET", "/recommend/{userID}", _recommend),
    Route("GET", "/recommendToMany/{userIDs:+}", _recommend_to_many),
    Route("GET", "/recommendToAnonymous/{itemIDs:+}", _recommend_to_anonymous),
    Route("GET", "/recommendWithContext/{userID}/{itemIDs:+}",
          _recommend_with_context),
    Route("GET", "/similarity/{itemIDs:+}", _similarity),
    Route("GET", "/similarityToItem/{toItemID}/{itemIDs:+}",
          _similarity_to_item),
    Route("GET", "/estimate/{userID}/{itemIDs:+}", _estimate),
    Route("GET", "/estimateForAnonymous/{toItemID}/{itemIDs:+}",
          _estimate_for_anonymous),
    Route("GET", "/because/{userID}/{itemID}", _because),
    Route("GET", "/mostSurprising/{userID}", _most_surprising),
    Route("GET", "/mostActiveUsers", _most_active_users),
    Route("GET", "/mostPopularItems", _most_popular_items),
    Route("GET", "/popularRepresentativeItems", _popular_representative_items),
    # reference-exact paths (AllUserIDs.java:33-37 is @Path("/user") +
    # @Path("/allIDs") -> /user/allIDs; likewise /item/allIDs); the
    # flat spellings are kept as aliases
    Route("GET", "/user/allIDs", _all_user_ids),
    Route("GET", "/item/allIDs", _all_item_ids),
    Route("GET", "/allUserIDs", _all_user_ids),
    Route("GET", "/allItemIDs", _all_item_ids),
    Route("GET", "/knownItems/{userID}", _known_items),
    Route("POST", "/pref/{userID}/{itemID}", _pref_post, mutates=True),
    Route("DELETE", "/pref/{userID}/{itemID}", _pref_delete, mutates=True),
    Route("POST", "/ingest", _ingest, mutates=True),
    console.console_route("Alternating Least Squares", [
        console.Endpoint("/recommend/{0}", ("userID",)),
        console.Endpoint("/recommendToAnonymous/{0}", ("itemID(=strength)",)),
        console.Endpoint("/similarity/{0}/{1}", ("itemID1", "itemID2")),
        console.Endpoint("/estimate/{0}/{1}", ("userID", "itemID")),
        console.Endpoint("/because/{0}/{1}", ("userID", "itemID")),
        console.Endpoint("/knownItems/{0}", ("userID",)),
        console.Endpoint("/mostActiveUsers"),
        console.Endpoint("/mostPopularItems"),
        console.Endpoint("/allUserIDs"),
        console.Endpoint("/allItemIDs"),
        console.Endpoint("/ready"),
    ]),
]
