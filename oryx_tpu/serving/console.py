"""Per-app HTML console served at the context root.

Reference capability: each serving app ships a small interactive
console page (app/oryx-app-serving/.../AbstractConsoleResource.java:35
wrapping an app fragment in a shared header/footer, served as
text/html with X-Frame-Options).  This is a fresh single-page
implementation: one template, endpoint descriptors per app, fetch()-
based query execution with the raw JSON response shown inline.
"""

from __future__ import annotations

import json

from ..lambda_rt.http import HtmlResponse, Request, Route

__all__ = ["console_route", "Endpoint"]


class Endpoint:
    """One console row: endpoint path template + input field names.

    ``path`` uses ``{0}``, ``{1}``… placeholders filled from the field
    values; ``query`` lists optional query parameters offered as a
    free-text suffix box.
    """

    def __init__(self, path: str, fields: tuple[str, ...] = (),
                 method: str = "GET", note: str = ""):
        self.path = path
        self.fields = fields
        self.method = method
        self.note = note

    def spec(self) -> dict:
        return {"path": self.path, "fields": list(self.fields),
                "method": self.method, "note": self.note}


_PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8"/>
<title>{title} — oryx-tpu serving console</title>
<style>
  body {{ font-family: system-ui, sans-serif; margin: 2rem auto;
         max-width: 60rem; color: #1a2733; }}
  h1 {{ font-size: 1.3rem; }} h1 small {{ color: #7a8793; font-weight: normal; }}
  table {{ border-collapse: collapse; width: 100%; }}
  td {{ padding: .35rem .5rem; border-bottom: 1px solid #e4e9ee; }}
  code {{ color: #0b5394; }}
  input {{ border: 1px solid #b8c2cc; border-radius: 3px; padding: .2rem .4rem; }}
  button {{ border: 1px solid #0b5394; background: #0b5394; color: white;
           border-radius: 3px; padding: .2rem .7rem; cursor: pointer; }}
  pre {{ background: #f4f7fa; border: 1px solid #e4e9ee; border-radius: 4px;
        padding: .8rem; white-space: pre-wrap; word-break: break-all;
        min-height: 3rem; }}
  .status {{ color: #7a8793; font-size: .85rem; }}
</style>
</head>
<body>
<h1>{title} <small>serving console</small></h1>
<table id="endpoints"></table>
<h2 style="font-size:1rem">Response <span class="status" id="status"></span></h2>
<pre id="out">(run a query)</pre>
<script>
const ENDPOINTS = {endpoints_json};
const table = document.getElementById("endpoints");
ENDPOINTS.forEach((ep, i) => {{
  const row = table.insertRow();
  row.insertCell().innerHTML = "<code>" + ep.method + " " + ep.path + "</code>";
  const cell = row.insertCell();
  ep.fields.forEach((f, j) => {{
    cell.innerHTML += '<input size="10" placeholder="' + f +
        '" id="f' + i + '_' + j + '"/> ';
  }});
  cell.innerHTML += '<input size="14" placeholder="query string" id="q' +
      i + '"/>';
  const go = row.insertCell();
  go.innerHTML = '<button onclick="run(' + i + ')">run</button>';
  if (ep.note) row.insertCell().textContent = ep.note;
}});
async function run(i) {{
  const ep = ENDPOINTS[i];
  let path = ep.path;
  ep.fields.forEach((f, j) => {{
    path = path.replace("{{" + j + "}}",
        encodeURIComponent(document.getElementById("f" + i + "_" + j).value));
  }});
  const q = document.getElementById("q" + i).value;
  if (q) path += "?" + q;
  const status = document.getElementById("status");
  status.textContent = "…";
  try {{
    const resp = await fetch(path, {{method: ep.method}});
    status.textContent = resp.status + " " + resp.statusText;
    const text = await resp.text();
    try {{ document.getElementById("out").textContent =
        JSON.stringify(JSON.parse(text), null, 2); }}
    catch (e) {{ document.getElementById("out").textContent = text; }}
  }} catch (e) {{
    status.textContent = "error";
    document.getElementById("out").textContent = String(e);
  }}
}}
</script>
</body>
</html>
"""


def console_route(title: str, endpoints: list[Endpoint]) -> Route:
    """The app's ``GET /`` console page (reference:
    AbstractConsoleResource serving index.html per app)."""
    page = _PAGE.format(
        title=title,
        endpoints_json=json.dumps([e.spec() for e in endpoints]))

    def _console(req: Request):
        return HtmlResponse(page)

    return Route("GET", "/", _console)
