"""Framework-level serving resources: readiness, the error page, and
shared helpers.

Reference: app/oryx-app-serving/.../Ready.java:34 (HEAD/GET /ready ->
200/503 against min-model-load-fraction),
AbstractOryxResource.java:52-... (model gating, input send),
ErrorResource.java:36 (the error-page forward target).
"""

from __future__ import annotations

import zlib
from typing import Any

from ..common import clock as clockmod
from ..api.serving import OryxServingException
from ..lambda_rt.http import (HtmlResponse, Request, Route, TextResponse,
                              render_error_page)
from ..obs.server import (admin_diagnose, admin_flight,
                          admin_flight_dump, admin_profile,
                          admin_region, admin_slo, admin_tail,
                          admin_traces, prometheus_response)
from ..resilience.policy import CircuitOpenError, resilience_snapshot

__all__ = ["ROUTES", "get_serving_model", "send_input",
           "send_input_many"]


def get_serving_model(req: Request) -> Any:
    """The current model, or 503 until enough is loaded
    (reference: AbstractOryxResource.getServingModel :76-96)."""
    manager = req.context["model_manager"]
    model = manager.get_model()
    if model is not None:
        fraction = model.get_fraction_loaded()
        if fraction >= req.context["min_model_load_fraction"]:
            return model
    raise OryxServingException(503, "Model not available yet")


def send_input(req: Request, line: str) -> None:
    send_input_many(req, [line])


def send_input_many(req: Request, lines: list[str]) -> None:
    """Durably append ``lines`` to the input topic — one pipelined
    ``send_many`` produce, so a multi-line ``/ingest`` costs one broker
    call instead of one per record.  A normal return means every
    record is in the input topic (202 = durable); any failure maps to
    503 (retry), never a partial silent loss.  The ingest admission
    gate (serving/ingest.py) sheds HERE, inside the write path only,
    so health/admin/read routes are never gated."""
    producer = req.context.get("input_producer")
    if producer is None:
        raise OryxServingException(403, "no input topic configured")
    # record headers (kafka/api.py), preserved PER RECORD: `ts` stamps
    # ingest wall-clock so the speed layer can measure ingest→servable
    # freshness end to end; `traceparent` carries a sampled request's
    # trace context so the fold-in that makes each record servable
    # joins its trace
    headers = {"ts": str(int(clockmod.now() * 1000))}
    tracer = req.context.get("tracer")
    if tracer is not None:
        cur = tracer.current()
        if cur.sampled:
            headers["traceparent"] = cur.traceparent()
    # key = hash of the message, so identical records land in the same
    # partition (reference: AbstractOryxResource.sendInput :68 sends
    # Integer.toHexString(message.hashCode()) as the key)
    entries = [(format(zlib.crc32(line.encode("utf-8")), "x"), line,
                dict(headers)) for line in lines]
    gate = req.context.get("ingest_gate")
    try:
        if gate is not None:
            with gate.admitted(req.context.get("metrics"),
                               n=len(entries)):
                _produce(producer, entries)
        else:
            _produce(producer, entries)
    except OryxServingException:
        raise  # the gate's shed (503 + Retry-After) passes through
    except CircuitOpenError as e:
        # broker presumed down: degrade the write surface to fast 503s
        # (not 500 — the request was fine; the dependency is not) and
        # let the breaker's half-open probe restore it without restart
        raise OryxServingException(503, f"input unavailable: {e}") from e
    except Exception as e:  # noqa: BLE001 — any broker fault degrades,
        raise OryxServingException(                   # it doesn't error
            503, f"input send failed: {e}") from e


def _produce(producer, entries: list[tuple[str, str, dict]]) -> None:
    if len(entries) == 1:
        key, line, headers = entries[0]
        producer.send(key, line, headers=headers)
        return
    send_many = getattr(producer, "send_many", None)
    if send_many is not None:
        send_many(entries)
        return
    for key, line, headers in entries:
        producer.send(key, line, headers=headers)


def _ready(req: Request):
    manager = req.context["model_manager"]
    model = manager.get_model()
    if model is not None and (model.get_fraction_loaded()
                              >= req.context["min_model_load_fraction"]):
        return None  # 204-ish empty 200
    raise OryxServingException(503, "Model not available yet")


def _error(req: Request):
    """Explicit error-page resource: renders error info carried in the
    query string, where the reference's container forwards errored
    requests with RequestDispatcher.ERROR_* attributes
    (ErrorResource.java:36; wired as the error page for every status in
    ServingLayer.java:305-311).  The hand-rolled server renders
    in-flight errors directly through render_error_page, so this
    endpoint is the addressable form of the same page."""
    code = req.q1("code", "")
    status = int(code) if code and code.isdigit() else 200
    payload, ctype = render_error_page(
        status, req.q1("uri"), req.q1("message"),
        req.headers.get("Accept", ""))
    if ctype.startswith("text/html"):
        return status, HtmlResponse(payload.decode())
    return status, TextResponse(payload.decode())


def _metrics(req: Request):
    """Per-route request counts, error counts, and latency percentiles
    (the reference exposes only logs + Spark UI — SURVEY §5.1/5.5; this
    is the serving-side step-metrics surface ops parity needs), plus the
    request micro-batcher's live pacing state and the streaming top-k
    certificate-fallback counter — the two internals an operator needs
    when throughput or result-exactness questions come up."""
    registry = req.context.get("metrics")
    if registry is None:
        raise OryxServingException(404, "metrics not enabled")
    # ?format=prometheus / prometheus-json (obs/server.py): the text
    # exposition and the mergeable structured snapshot the cluster
    # gateway scrapes; plain JSON stays the default
    prom = prometheus_response(req, registry)
    if prom is not None:
        return prom
    model = req.context["model_manager"].get_model()
    out = {
        "routes": registry.snapshot(),
        "model_fraction_loaded":
            model.get_fraction_loaded() if model is not None else 0.0,
    }
    batcher = req.context.get("top_n_batcher")
    if batcher is not None:
        out["scoring_batcher"] = batcher.stats()
    counters = registry.counters_snapshot()
    if counters:
        out["counters"] = counters
    # sharded-cluster replica: shard coordinates + generation, so an
    # operator (and the gateway bench) can see per-replica catalog
    # state without the router in between
    mgr = req.context["model_manager"]
    if getattr(mgr, "shard_count", 1) > 1 or hasattr(mgr, "generation"):
        cluster = {"generation": getattr(mgr, "generation", 0)}
        if getattr(mgr, "shard_count", 1) > 1:
            cluster.update(shard=mgr.shard_index, of=mgr.shard_count,
                           skipped_remote_items=getattr(
                               mgr, "skipped_remote_items", 0))
        out["cluster"] = cluster
    # named retry / circuit-breaker counters (resilience.policy) — the
    # evidence surface for "is the breaker open, how often do we retry"
    out["resilience"] = resilience_snapshot()
    # app-agnostic hook: a serving model may contribute its own gauges
    # (e.g. the ALS model's streaming top-k fallback counter)
    app_metrics = getattr(model, "metrics", None)
    if callable(app_metrics):
        out["model_metrics"] = app_metrics()
    # consumer-side integrity counters: poison updates / corrupt model
    # documents the manager refused (numerical trust boundary evidence)
    manager = req.context["model_manager"]
    rejected_updates = getattr(manager, "rejected_updates", None)
    if rejected_updates is not None:
        out["model_integrity"] = {
            "rejected_updates": rejected_updates,
            "rejected_models": getattr(manager, "rejected_models", 0),
        }
    # lambda freshness gauges (obs/freshness.py): consumer lag, model
    # generation age — evaluated on read, best-effort
    gauges = registry.gauges_snapshot()
    if gauges:
        out["freshness"] = gauges
    tracer = req.context.get("tracer")
    if tracer is not None:
        out["obs"] = {"trace_record_failures": tracer.record_failures}
    # continuous device-time accounting (obs/device_time.py): which
    # kernel route owned the device, and how busy it is
    acct = req.context.get("device_time")
    if acct is not None:
        out["device_time"] = acct.snapshot()
    return out


ROUTES = [
    Route("GET", "/ready", _ready),
    Route("GET", "/error", _error),
    Route("GET", "/metrics", _metrics),
    Route("GET", "/admin/traces", admin_traces),
    # tail anatomy + SLO alert surface (obs/anatomy.py, obs/slo.py);
    # both 404 until their config gates open
    Route("GET", "/admin/tail", admin_tail),
    Route("GET", "/admin/slo", admin_slo),
    # region identity (multi-region serving, docs/SCALING.md)
    Route("GET", "/admin/region", admin_region),
    # flight recorder + auto-triage (obs/flight.py, obs/diagnose.py);
    # /admin/flight 404s until oryx.obs.flight.dir opens the gate
    Route("GET", "/admin/flight", admin_flight),
    Route("GET", "/admin/diagnose", admin_diagnose),
    # mutating: captures device state to disk — read-only mode and
    # DIGEST auth (when configured) both gate it
    Route("GET", "/admin/profile", admin_profile, mutates=True),
    # mutating for the same reason: writes a bundle to the store
    Route("POST", "/admin/flight/dump", admin_flight_dump,
          mutates=True),
]
