"""Request micro-batcher: concurrent /recommend-family requests share
one device dispatch.

Reference equivalent: SURVEY §2.14 P6 — Tomcat's 400-thread pool fans a
single request out across cores (ServingLayer.java:235); the TPU-native
inversion batches many concurrent requests into ONE MXU matmul
(`ALSServingModel.top_n_batch`).

Design: adaptive queue-drain batching bounded by a measured in-flight
cap.  Handler threads enqueue a scoring job and block; dispatcher
threads drain whatever is queued and issue one batched kernel call
each.  The cap — ceil(round_trip / service_time) + 1, both learned
from dispatch walls and completion gaps — is what makes batching
adapt to model size: beyond it, extra dispatches only stack
device-queue latency (observed before the cap existed: free
dispatchers shredded a 5M-item model's queue into tiny batches that
serialized on the device, 3% of achievable throughput with 3 s
device-queue latency).  A blocked dispatcher wakes on the next
completion and drains everything that queued during one service
interval, so batch size tracks the arrival rate under load with no
explicit pacing.  Below the cap, a request is held only a couple of
milliseconds (zero on a locally attached chip) so a synchronized
burst coalesces while an unloaded request keeps its latency at
round-trip + exec — a service-interval hold here would cost more
than the device time itself behind a high-latency tunnel.
"""

from __future__ import annotations

import threading
from typing import Iterable

import numpy as np

from ..common import clock as clockmod
from ..resilience import faults
from ..resilience.policy import Deadline, DeadlineExceeded

__all__ = ["TopNBatcher"]

# exec-time EWMA clamps: below 0.5 ms pacing is irrelevant; above this
# cap a single anomalous stall (e.g. a mid-run recompile) cannot freeze
# dispatching for minutes
_MIN_EXEC_S = 0.0005
_MAX_EXEC_S = 5.0


class _Job:
    __slots__ = ("model", "how_many", "vector", "exclude", "done",
                 "result", "error", "t_enq", "deadline", "trace_ctx")

    def __init__(self, model, how_many: int, vector: np.ndarray,
                 exclude: set[str], deadline: Deadline | None = None,
                 trace_ctx: tuple[str, str] | None = None):
        self.model = model
        self.how_many = how_many
        self.vector = vector
        self.exclude = exclude
        self.done = threading.Event()
        self.result: list[tuple[str, float]] | None = None
        self.error: BaseException | None = None
        self.t_enq = clockmod.monotonic()
        self.deadline = deadline
        # (trace_id, parent_span_id) captured at submit on sampled
        # requests; None (the overwhelmingly common case) costs nothing
        self.trace_ctx = trace_ctx


class TopNBatcher:
    """Coalesce concurrent dot-product top-N requests into batched
    device calls.  Safe across model hot-swaps: jobs carry their model,
    and each drain groups jobs by model identity."""

    def __init__(self, max_batch: int = 1024, pipeline: int = 32,
                 idle_wait_s: float | None = None, tracer=None,
                 accountant=None):
        """``pipeline`` dispatcher threads keep that many batched device
        calls in flight at once: dispatch latency (dominated by the
        host<->device round trip) overlaps instead of serializing, so
        sustained throughput ~= mean_batch x pipeline / round_trip.
        Depth must cover the transport's round trip x the dispatch rate;
        32 measured best through a high-latency device tunnel and idle
        depth is just parked threads on a locally attached chip;
        configurable via oryx.serving.api.scoring-pipeline-depth.

        ``idle_wait_s`` caps how long a below-capacity server holds a
        request hoping a burst coalesces.  None (default) adapts to
        the measured transport: behind a high-latency tunnel the cap
        is 2 ms (enough for a synchronized burst to land, invisible
        next to the round trip), on a locally attached chip (measured
        round trip under ~5 ms) it is 0 — immediate dispatch.
        Configurable via oryx.serving.api.batch-idle-wait-ms
        (-1 = adaptive).

        ``tracer`` (obs/trace.py, or None) splits each sampled
        request's batcher residence into a queue-wait span and a
        device-execute span — the evidence that separates "the device
        is slow" from "the queue is deep".

        ``accountant`` (obs/device_time.py, or None) books every
        batched device-execute bracket as route-class ``serve`` time
        against the model's kernel route and generation — the
        continuous occupancy accounting behind
        ``device_busy_fraction``."""
        self.max_batch = max_batch
        self._tracer = tracer
        self._accountant = accountant
        self._idle_wait = idle_wait_s
        self._cond = threading.Condition()
        self._pending: list[_Job] = []
        self._stopped = False
        # service-rate pacing state (all under _cond)
        self._in_flight = 0
        self._last_dispatch = 0.0
        self._last_completion = 0.0
        self._exec_ewma = _MIN_EXEC_S  # optimistic until measured
        # min observed dispatch wall time ~= round_trip + one exec; the
        # in-flight target ceil(round_trip / exec) + 1 keeps the device
        # continuously fed without stacking a deep on-device queue
        self._wall_min = float("inf")
        self._threads = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"TopNBatcher-{i}")
            for i in range(max(1, pipeline))]
        for t in self._threads:
            t.start()
        # drain-size histogram, exposed for tests and the metrics surface
        self.batch_sizes: list[int] = []
        self.total_dispatches = 0
        # deadline sheds: refused at submit or expired while queued
        self.deadline_rejects = 0
        # measured queue wait (enqueue -> drain pickup), EWMA over
        # recent drains: the overload signal replicas report upstream
        # for the router's admission control (under _cond)
        self._qwait_ewma = 0.0
        self._qwait_at = 0.0

    def top_n(self, model, how_many: int, user_vector: np.ndarray,
              exclude: Iterable[str] = (),
              deadline: Deadline | None = None) -> list[tuple[str, float]]:
        """Blocking submit; returns the same pairs as ``model.top_n``
        (dot-product scores; on an LSH-configured model the batched
        dispatch applies the same Hamming-ball candidate mask the
        single-request path would).

        A ``deadline`` (resilience.policy.Deadline, minted at the HTTP
        front end) is enforced at the two queueing edges: an already-
        expired request is refused before it queues, and a request whose
        budget runs out while waiting is shed at dispatch instead of
        spending device time on an answer nobody is waiting for.  Both
        raise DeadlineExceeded (503 at the serving surface)."""
        if deadline is not None and deadline.expired:
            with self._cond:
                self.deadline_rejects += 1
            raise DeadlineExceeded("request deadline expired before "
                                   "scoring was queued")
        trace_ctx = None
        if self._tracer is not None:
            # submit runs on the request's handler thread, so the
            # thread-current span is the request span; its context is
            # captured here because the dispatcher thread that records
            # the queue-wait/device-execute split has no thread-local
            # trace state of its own
            cur = self._tracer.current()
            if cur.sampled:
                trace_ctx = (cur.trace_id, cur.span_id)
        job = _Job(model, how_many,
                   np.asarray(user_vector, dtype=np.float32), set(exclude),
                   deadline=deadline, trace_ctx=trace_ctx)
        with self._cond:
            if self._stopped:
                # shutdown race: keep-alive handler threads may outlive
                # close(); degrade to an unbatched dispatch, not a 500
                stopped = True
            else:
                stopped = False
                self._pending.append(job)
                self._cond.notify()
        if stopped:
            return model.top_n_batch([how_many], job.vector[None, :],
                                     [job.exclude])[0]
        job.done.wait()  # wall-clock: caller blocks on a real worker thread
        if job.error is not None:
            raise job.error
        return job.result

    def recent_queue_wait_ms(self) -> float:
        """The batcher's current queue-wait estimate in ms: the larger
        of the recent-drain EWMA (decayed to 0 after 5 idle seconds)
        and the LIVE age of the oldest still-queued job — so a queue
        that stopped draining reports a growing wait, not the stale
        average of better times."""
        now = clockmod.monotonic()
        with self._cond:
            ew = self._qwait_ewma if now - self._qwait_at <= 5.0 else 0.0
            oldest = (now - self._pending[0].t_enq) if self._pending \
                else 0.0
        return max(ew, oldest) * 1000.0

    def stats(self) -> dict:
        """Live pacing/batching state for the /metrics surface."""
        qw = self.recent_queue_wait_ms()
        with self._cond:
            sizes = self.batch_sizes[-1000:]
            return {
                "dispatches": self.total_dispatches,
                "queue_wait_ms": round(qw, 2),
                "mean_recent_batch": round(sum(sizes) / len(sizes), 1)
                if sizes else 0.0,
                "service_time_ms": round(self._exec_ewma * 1e3, 2),
                "round_trip_floor_ms": round(self._wall_min * 1e3, 1)
                if self._wall_min != float("inf") else None,
                "in_flight": self._in_flight,
                "in_flight_target": self._in_flight_target(),
                "pending": len(self._pending),
                "deadline_rejects": self.deadline_rejects,
            }

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(5.0)

    # -- dispatcher ----------------------------------------------------------

    def _in_flight_target(self) -> int:
        """How many dispatches keep the device continuously busy: enough
        to cover the transport round trip at the current service rate,
        plus one.  More than this only deepens the on-device queue (each
        extra dispatch adds a full service time to every later request's
        latency).  Called inside the dispatchers' wait loops — plain
        float math, no numpy scalars (they cost microseconds each)."""
        wall_min = self._wall_min
        if wall_min == float("inf"):
            return len(self._threads)  # unmeasured: let it rip once
        rtt = wall_min - self._exec_ewma
        if rtt <= 0.0:
            return 2
        return min(len(self._threads),
                   1 + max(1, -int(-rtt // self._exec_ewma)))

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stopped:
                    if not self._pending:
                        self._cond.wait()  # wall-clock: Condition poll on the real dispatch thread
                        continue
                    # Hold-time is measured from the oldest pending
                    # arrival's age, not time since the last dispatch —
                    # a stale last-dispatch timestamp after an idle gap
                    # must not extend the hold.
                    age = clockmod.monotonic() - self._pending[0].t_enq
                    full = len(self._pending) >= self.max_batch
                    if self._in_flight >= self._in_flight_target():
                        # at the in-flight cap: a full queue must NOT
                        # add dispatches — extra depth only stacks
                        # device-queue latency onto every later request.
                        # Batching under load comes from HERE, not from
                        # pacing: a blocked dispatcher wakes on the next
                        # completion and drains everything that queued
                        # during one service interval.
                        self._cond.wait()  # wall-clock: Condition poll on the real dispatch thread
                        continue
                    # below the in-flight cap: hold only briefly so a
                    # synchronized burst coalesces, then go.  A lone
                    # request on an unloaded server must NOT pay a
                    # service-interval hold — the tunnel-learned
                    # exec EWMA runs ~10x the true device time, and
                    # that hold was most of the unloaded p50 above the
                    # transport floor (VERDICT r04 #2).  With a locally
                    # attached chip (tiny measured round trip) don't
                    # hold at all.
                    cap = self._idle_wait
                    if cap is None:
                        rtt = self._wall_min - self._exec_ewma
                        cap = 0.002 if rtt > 0.005 else 0.0
                    wait = min(cap, self._exec_ewma / 8) - age
                    if full or wait <= 0:
                        break
                    self._cond.wait(wait)  # wall-clock: Condition poll on the real dispatch thread
                if self._stopped:
                    jobs, self._pending = self._pending, []
                else:
                    jobs = self._pending[:self.max_batch]
                    del self._pending[:self.max_batch]
                    self._in_flight += 1
                    self._last_dispatch = clockmod.monotonic()
                stopped = self._stopped
            scored = 0
            if jobs:
                t0 = clockmod.monotonic()
                scored = self._dispatch(jobs)
                wall = clockmod.monotonic() - t0
            if not stopped:
                with self._cond:
                    self._in_flight -= 1
                    if not scored:
                        # every job was deadline-shed: no device call
                        # happened, and folding the near-zero wall into
                        # the estimators would collapse _wall_min /
                        # _exec_ewma and disable coalescing long after
                        # the deadline burst ends
                        self._cond.notify(2)
                        continue
                    now = clockmod.monotonic()
                    # decay toward recent walls so a transient stall
                    # (compile, GC) cannot pin the round-trip estimate
                    self._wall_min = min(self._wall_min * 1.02, wall)
                    if self._last_completion:
                        gap = now - self._last_completion
                        if self._in_flight > 0 and gap < _MAX_EXEC_S:
                            # overlapped completions: the gap measures
                            # the device's per-dispatch service time
                            self._exec_ewma = min(_MAX_EXEC_S, max(
                                _MIN_EXEC_S,
                                0.7 * self._exec_ewma + 0.3 * gap))
                    # a dispatch's whole wall (round trip + exec) upper-
                    # bounds exec: clamping lets the estimate relearn
                    # DOWNWARD after a hot-swap to a smaller model or an
                    # anomalous gap, where gap-based learning alone
                    # would lock pacing into serial dispatch forever
                    self._exec_ewma = max(_MIN_EXEC_S,
                                          min(self._exec_ewma, wall))
                    self._last_completion = now
                    # wake a couple of waiters, not the whole pipeline:
                    # notify_all costs O(threads) lock churn per
                    # completion, and pacing waiters self-wake on their
                    # timeout anyway
                    self._cond.notify(2)
            if stopped:
                return

    def _record_spans(self, group: list[_Job], t_exec: float,
                      t_done: float, status: str) -> None:
        """Queue-wait / device-execute spans for the sampled jobs of a
        drained group.  Recorded retroactively from stored monotonic
        stamps (the dispatcher has no thread-local trace context), and
        strictly best-effort — the tracer absorbs recorder failures."""
        traced = [j for j in group if j.trace_ctx is not None]
        if not traced:
            return
        route = getattr(group[0].model, "kernel_route_label", None)
        exec_attrs = {"batch_size": len(group)}
        if route:
            # which measured phase-A kernel variant served this drain
            # (app/als/kernel_router.py's dispatch decision)
            exec_attrs["kernel_route"] = route
        for j in traced:
            self._tracer.record_span("serving.queue_wait", j.trace_ctx,
                                     j.t_enq, t_exec)
            self._tracer.record_span("serving.device_execute",
                                     j.trace_ctx, t_exec, t_done,
                                     dict(exec_attrs), status)

    def _dispatch(self, jobs: list[_Job]) -> int:
        """Score a drained batch; returns how many jobs actually reached
        the device (0 = all shed, caller must not learn pacing from it)."""
        # shed jobs whose budget expired while queued: their client has
        # already given up, and scoring them would tax every live job in
        # the same drain with their share of the device time
        expired = [j for j in jobs
                   if j.deadline is not None and j.deadline.expired]
        if expired:
            with self._cond:
                self.deadline_rejects += len(expired)
            for j in expired:
                j.error = DeadlineExceeded(
                    "request deadline expired while queued")
                j.done.set()
            jobs = [j for j in jobs if j.error is None]
        t_pickup = clockmod.monotonic()
        if jobs:
            # queue wait of this drain = the oldest job's enqueue->pickup
            # age; EWMA'd so the admission signal tracks load, not one
            # straggler.  Sampled BEFORE the dispatch seam below: the
            # emulated device delay is service time, and folding it into
            # the wait would inflate the admission signal by one full
            # dispatch even with an empty queue
            qw = max(t_pickup - j.t_enq for j in jobs)
            with self._cond:
                self._qwait_ewma = 0.7 * self._qwait_ewma + 0.3 * qw
                self._qwait_at = t_pickup
        # chaos / device-emulation seam: one fire per drained dispatch.
        # mode=delay stands in for per-dispatch device time the host
        # does not burn CPU on — bench/gateway.py stages it to model
        # fixed-rate accelerators on a shared CPU box; mode=error fails
        # the whole drain (surfaced per job, never killing the
        # dispatcher thread)
        try:
            faults.fire("serving-scan-dispatch")
        except Exception as e:  # noqa: BLE001 — injected
            for j in jobs:
                j.error = e
                j.done.set()
            return 0
        by_model: dict[int, list[_Job]] = {}
        for j in jobs:
            by_model.setdefault(id(j.model), []).append(j)
        # the device window opens at drain PICKUP (before the emulation
        # seam): like the admission EWMA above, the emulated device
        # delay is service time, so the recorded queue_wait/
        # device_execute split must put it on the device side — tail
        # attribution (obs/anatomy.py) otherwise blames the queue for
        # a slow device.  Groups after the first open at the previous
        # group's completion.
        next_exec_start = t_pickup
        for group in by_model.values():
            model = group[0].model
            t_exec = next_exec_start
            status = "ok"
            try:
                results = model.top_n_batch(
                    [j.how_many for j in group],
                    np.stack([j.vector for j in group]),
                    [j.exclude for j in group])
                for j, r in zip(group, results):
                    j.result = r
            except BaseException as e:  # noqa: BLE001 — surfaced per job
                status = "error"
                for j in group:
                    j.error = e
            next_exec_start = clockmod.monotonic()
            if self._accountant is not None:
                # continuous occupancy: the same bracket the
                # device_execute span measures, booked as serve-class
                # device time against the model's route + generation
                self._accountant.note(
                    "serve",
                    getattr(model, "kernel_route_label", None),
                    getattr(model, "generation", None),
                    next_exec_start - t_exec)
            if self._tracer is not None:
                self._record_spans(group, t_exec, next_exec_start,
                                   status)
            with self._cond:
                # under the lock: up to `pipeline` dispatcher threads
                # land here concurrently, and a bare += loses updates
                self.batch_sizes.append(len(group))
                self.total_dispatches += 1
                if len(self.batch_sizes) > 10000:
                    del self.batch_sizes[:5000]
            for j in group:
                j.done.set()
        return len(jobs)
