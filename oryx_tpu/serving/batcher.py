"""Request micro-batcher: concurrent /recommend-family requests share
one device dispatch.

Reference equivalent: SURVEY §2.14 P6 — Tomcat's 400-thread pool fans a
single request out across cores (ServingLayer.java:235); the TPU-native
inversion batches many concurrent requests into ONE MXU matmul
(`ALSServingModel.top_n_batch`).

Design: adaptive queue-drain batching.  Handler threads enqueue a
scoring job and block; a single dispatcher thread drains whatever is
queued and issues one batched kernel call.  While that call is in
flight, new jobs accumulate — the device's own latency IS the batching
window, so an idle server adds no artificial delay (a lone request is
dispatched immediately as a batch of one) and a saturated server
coalesces aggressively.
"""

from __future__ import annotations

import threading
from typing import Iterable

import numpy as np

__all__ = ["TopNBatcher"]


class _Job:
    __slots__ = ("model", "how_many", "vector", "exclude", "done",
                 "result", "error")

    def __init__(self, model, how_many: int, vector: np.ndarray,
                 exclude: set[str]):
        self.model = model
        self.how_many = how_many
        self.vector = vector
        self.exclude = exclude
        self.done = threading.Event()
        self.result: list[tuple[str, float]] | None = None
        self.error: BaseException | None = None


class TopNBatcher:
    """Coalesce concurrent dot-product top-N requests into batched
    device calls.  Safe across model hot-swaps: jobs carry their model,
    and each drain groups jobs by model identity."""

    def __init__(self, max_batch: int = 1024, pipeline: int = 8):
        """``pipeline`` dispatcher threads keep that many batched device
        calls in flight at once: dispatch latency (dominated by the
        host<->device round trip) overlaps instead of serializing, so
        sustained throughput ~= mean_batch x pipeline / round_trip.
        Depth 8 is the measured sweet spot on a single chip (4 stalls on
        the round trip, 16 fragments batches below dispatch overhead);
        configurable via oryx.serving.api.scoring-pipeline-depth."""
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._pending: list[_Job] = []
        self._stopped = False
        self._threads = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"TopNBatcher-{i}")
            for i in range(max(1, pipeline))]
        for t in self._threads:
            t.start()
        # drain-size histogram, exposed for tests and the metrics surface
        self.batch_sizes: list[int] = []

    def top_n(self, model, how_many: int, user_vector: np.ndarray,
              exclude: Iterable[str] = ()) -> list[tuple[str, float]]:
        """Blocking submit; returns the same pairs as ``model.top_n``
        (dot-product scores; on an LSH-configured model the batched
        dispatch applies the same Hamming-ball candidate mask the
        single-request path would)."""
        job = _Job(model, how_many,
                   np.asarray(user_vector, dtype=np.float32), set(exclude))
        with self._cond:
            if self._stopped:
                # shutdown race: keep-alive handler threads may outlive
                # close(); degrade to an unbatched dispatch, not a 500
                stopped = True
            else:
                stopped = False
                self._pending.append(job)
                self._cond.notify()
        if stopped:
            return model.top_n_batch([how_many], job.vector[None, :],
                                     [job.exclude])[0]
        job.done.wait()
        if job.error is not None:
            raise job.error
        return job.result

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(5.0)

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if self._stopped:
                    jobs, self._pending = self._pending, []
                else:
                    jobs = self._pending[:self.max_batch]
                    del self._pending[:self.max_batch]
                stopped = self._stopped
            if jobs:
                self._dispatch(jobs)
            if stopped:
                return

    def _dispatch(self, jobs: list[_Job]) -> None:
        by_model: dict[int, list[_Job]] = {}
        for j in jobs:
            by_model.setdefault(id(j.model), []).append(j)
        for group in by_model.values():
            model = group[0].model
            try:
                results = model.top_n_batch(
                    [j.how_many for j in group],
                    np.stack([j.vector for j in group]),
                    [j.exclude for j in group])
                for j, r in zip(group, results):
                    j.result = r
            except BaseException as e:  # noqa: BLE001 — surfaced per job
                for j in group:
                    j.error = e
            self.batch_sizes.append(len(group))
            if len(self.batch_sizes) > 10000:
                del self.batch_sizes[:5000]
            for j in group:
                j.done.set()
