"""Bounded-producer admission for the write path (the durable-ack
contract's other half).

``send_input`` blocks its handler thread until the broker append
returns, so a 202 means the record is durable in the input topic.  The
missing half of that contract is overload: with the broker slow or the
write rate past what it sustains, un-gated ingest stacks blocked
handler threads without bound — the same open-loop spiral the scatter
path's AdmissionController (cluster/admission.py) sheds.  This gate is
its write-path twin, wrapping ONLY the ``send_input`` /
``send_input_many`` produce (never health, admin, or read routes —
those must stay open so operators can see into an overloaded tier):

- **max-inflight-sends** — a hard cap on concurrently executing broker
  appends across the process; in-flight count IS the producer queue
  depth, because each send holds its handler thread.
- **send-lag-high-ms** — *measured* send lag: an EWMA of recent append
  durations.  When the broker demonstrably takes longer than the
  threshold per append AND a send is already in flight, new writes
  shed at the door before they join the convoy.  With nothing in
  flight there is no convoy to join, so the request is admitted as
  the probe whose measurement re-opens (or re-confirms) the gate —
  a latched-open gate with no traffic to re-measure it would shed
  forever.

Both gates 0 (the shipped default) = disabled.  A shed is a fast
``503`` with ``Retry-After`` (``OryxServingException.headers``) and an
``ingest_sheds`` count — so the ingest contract becomes "202 means
durable in the input topic, 503 means retry — nothing in between".
"""

from __future__ import annotations

import threading

from ..api.serving import OryxServingException
from ..common import clock as clockmod

__all__ = ["IngestGate"]

# EWMA weight of the newest send sample (~last 10 sends dominate):
# reactive enough to open the gate within a burst, smooth enough that
# one slow append doesn't shed
_ALPHA = 0.2


class IngestGate:
    """``with gate.admitted(metrics, n):`` around the produce;
    constructed from ``oryx.serving.ingest.*``."""

    def __init__(self, config, metrics=None):
        i = "oryx.serving.ingest"
        self.max_inflight = config.get_int(f"{i}.max-inflight-sends")
        self.send_lag_high_ms = config.get_int(f"{i}.send-lag-high-ms")
        self.retry_after_sec = max(1, config.get_int(
            f"{i}.retry-after-sec"))
        self._metrics = metrics
        self._lock = threading.Lock()
        self.inflight = 0
        self.sheds = 0
        self._ewma_ms: float | None = None

    @property
    def enabled(self) -> bool:
        return self.max_inflight > 0 or self.send_lag_high_ms > 0

    def send_lag_ms(self) -> float | None:
        with self._lock:
            return None if self._ewma_ms is None \
                else round(self._ewma_ms, 3)

    def admitted(self, metrics=None, n: int = 1) -> "_Admission":
        """Admission around one produce of ``n`` records; raises the
        503-with-Retry-After OryxServingException on shed.  The send
        duration measured inside feeds the lag EWMA."""
        with self._lock:
            # the lag gate needs inflight > 0: with no send in flight
            # there is no convoy, and this request is the probe whose
            # measured duration re-opens a gate the EWMA latched
            shed = (self.max_inflight > 0
                    and self.inflight >= self.max_inflight) or \
                   (self.send_lag_high_ms > 0
                    and self.inflight > 0
                    and self._ewma_ms is not None
                    and self._ewma_ms > self.send_lag_high_ms)
            if shed:
                self.sheds += 1
            else:
                self.inflight += 1
        if shed:
            for m in (metrics, self._metrics):
                if m is not None:
                    # inc takes its own lock; called outside ours
                    m.inc("ingest_sheds")
                    break
            raise OryxServingException(
                503, "ingest overloaded; retry later",
                headers={"Retry-After": str(self.retry_after_sec)})
        return _Admission(self)

    def _finish(self, elapsed_ms: float) -> None:
        with self._lock:
            self.inflight -= 1
            self._ewma_ms = elapsed_ms if self._ewma_ms is None else \
                _ALPHA * elapsed_ms + (1.0 - _ALPHA) * self._ewma_ms

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "inflight": self.inflight,
                    "sheds": self.sheds,
                    "send_lag_ms": None if self._ewma_ms is None
                    else round(self._ewma_ms, 3),
                    "max_inflight_sends": self.max_inflight,
                    "send_lag_high_ms": self.send_lag_high_ms}


class _Admission:
    """Times the admitted produce; always releases, whatever raised."""

    def __init__(self, gate: IngestGate):
        self._gate = gate
        self._t0 = 0.0

    def __enter__(self) -> "_Admission":
        self._t0 = clockmod.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._gate._finish(
            (clockmod.monotonic() - self._t0) * 1000.0)
