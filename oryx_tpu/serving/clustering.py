"""Clustering REST endpoints.

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/clustering/Assign.java:52 (GET /assign/{datum} + POST bulk),
Add.java:43 (write datum to input topic), kmeans/DistanceToNearest.java:40.
"""

from __future__ import annotations

from ..api.serving import OryxServingException
from ..common import text as text_utils
from ..lambda_rt.http import Request, Route
from . import console
from .framework import get_serving_model, send_input

__all__ = ["ROUTES"]


def _model(req: Request):
    return get_serving_model(req)


def _tokens(datum: str) -> list[str]:
    if not datum:
        raise OryxServingException(400, "Data is needed to cluster")
    return text_utils.parse_delimited(datum, ",")


def _assign_get(req: Request):
    model = _model(req)
    try:
        return str(model.nearest_cluster_id(_tokens(req.params["datum"])))
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))


def _assign_post(req: Request):
    """Bulk assignment: one device kernel over all POSTed lines."""
    model = _model(req)
    lines = [ln.strip() for ln in req.body.decode().splitlines()
             if ln.strip()]
    rows = [_tokens(ln) for ln in lines]
    try:
        return [str(i) for i in model.nearest_cluster_ids(rows)]
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))


def _add(req: Request):
    _model(req)  # 503 gate
    datum = req.params["datum"]
    if not datum:
        raise OryxServingException(400, "Data is needed")
    send_input(req, datum)
    return None


def _add_post(req: Request):
    _model(req)
    lines = [ln.strip() for ln in req.body.decode().splitlines()
             if ln.strip()]
    for line in lines:
        send_input(req, line)
    return None


def _distance_to_nearest(req: Request):
    model = _model(req)
    try:
        vec_tokens = _tokens(req.params["datum"])
        from ..app.kmeans.common import features_from_tokens
        vec = features_from_tokens(vec_tokens, model.input_schema)
        _, dist = model.closest_cluster(vec)
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))
    return str(dist)


ROUTES = [
    Route("GET", "/assign/{datum}", _assign_get),
    Route("POST", "/assign", _assign_post),
    Route("GET", "/add/{datum}", _add),
    Route("POST", "/add", _add_post),
    Route("GET", "/distanceToNearest/{datum}", _distance_to_nearest),
    console.console_route("k-means Clustering", [
        console.Endpoint("/assign/{0}", ("datum (CSV)",)),
        console.Endpoint("/distanceToNearest/{0}", ("datum (CSV)",)),
        console.Endpoint("/add/{0}", ("datum (CSV)",)),
        console.Endpoint("/ready"),
    ]),
]
