"""Classification/regression REST endpoints (the RDF app's API).

Reference: app/oryx-app-serving/src/main/java/com/cloudera/oryx/app/
serving/classreg/Predict.java:52 (GET /predict/{datum} + POST bulk),
Train.java:42 (write training examples to the input topic),
rdf/ClassificationDistribution.java:53 (per-class probabilities),
rdf/FeatureImportance.java:46 (/feature/importance(/{n})).
"""

from __future__ import annotations

from ..api.serving import OryxServingException
from ..app.rdf.serving import RDFServingModel
from ..common import text as text_utils
from ..lambda_rt.http import Request, Route
from .als import IDValue
from . import console
from .framework import get_serving_model, send_input

__all__ = ["ROUTES"]


def _rdf_model(req: Request) -> RDFServingModel:
    model = get_serving_model(req)
    if not isinstance(model, RDFServingModel):
        raise OryxServingException(503, "Model not available yet")
    return model


def _tokens(datum: str) -> list[str]:
    if not datum:
        raise OryxServingException(400, "Missing input data")
    return text_utils.parse_delimited(datum, ",")


def _body_lines(req: Request) -> list[str]:
    return [ln.strip() for ln in req.body.decode().splitlines()
            if ln.strip()]


def _predict_get(req: Request):
    model = _rdf_model(req)
    try:
        return model.predict(_tokens(req.params["datum"]))
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))


def _predict_post(req: Request):
    """Bulk prediction: one batched device kernel over all lines."""
    model = _rdf_model(req)
    rows = [_tokens(line) for line in _body_lines(req)]
    if not rows:
        return []
    try:
        return model.predict_bulk(rows)
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))


def _train_datum(req: Request):
    # no model gate: training data must flow before the first model
    # exists (reference: Train.java writes the input topic directly)
    datum = req.params["datum"]
    if not datum:
        raise OryxServingException(400, "Missing input data")
    send_input(req, datum)
    return None


def _train_post(req: Request):
    for line in _body_lines(req):
        send_input(req, line)
    return None


def _classification_distribution(req: Request):
    model = _rdf_model(req)
    schema = model.input_schema
    if not schema.is_classification():
        raise OryxServingException(400, "Only applicable for classification")
    try:
        prediction = model.make_prediction(_tokens(req.params["datum"]))
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, str(e))
    target = schema.target_feature_index
    return [IDValue(model.encodings.decode(target, i), float(p))
            for i, p in enumerate(prediction.category_probabilities)]


def _predictor_importances(model: RDFServingModel) -> list[float]:
    """Importances indexed by PREDICTOR number (reference:
    RDFUpdate.countsToImportances sizes by getNumPredictors, so
    /feature/importance/{n} takes a predictor index — the target column
    is not a feature here).  The forest stores them all-features-indexed
    for PMML round-tripping; project down through the schema."""
    schema = model.input_schema
    imps = model.forest.feature_importances
    return [float(imps[schema.predictor_to_feature_index(p)])
            for p in range(schema.num_predictors)]


def _feature_importance_all(req: Request):
    return _predictor_importances(_rdf_model(req))


def _feature_importance_one(req: Request):
    importances = _predictor_importances(_rdf_model(req))
    try:
        number = int(req.params["featureNumber"])
    except ValueError:
        raise OryxServingException(400, "Bad feature number")
    if not 0 <= number < len(importances):
        raise OryxServingException(400, "Bad feature number")
    return importances[number]


ROUTES = [
    Route("GET", "/predict/{datum}", _predict_get),
    Route("POST", "/predict", _predict_post),
    Route("POST", "/train/{datum}", _train_datum, mutates=True),
    Route("POST", "/train", _train_post, mutates=True),
    Route("GET", "/classificationDistribution/{datum}",
          _classification_distribution),
    Route("GET", "/feature/importance", _feature_importance_all),
    Route("GET", "/feature/importance/{featureNumber}",
          _feature_importance_one),
    console.console_route("Random Decision Forest", [
        console.Endpoint("/predict/{0}", ("datum (CSV)",)),
        console.Endpoint("/classificationDistribution/{0}", ("datum (CSV)",)),
        console.Endpoint("/feature/importance"),
        console.Endpoint("/train/{0}", ("datum (CSV)",), method="POST"),
        console.Endpoint("/ready"),
    ]),
]
