"""Real-dataset adapter: consume MovieLens-format ratings files when
present, fall back to the planted-structure synthesizer otherwise.

This environment has no network egress, so benches synthesize at
MovieLens-20M shape by default (bench/train.py) — but a user WITH the
real files must be able to point the benches at them.  Set
``ORYX_ML_DATA=/path/to/ml-20m`` (or pass ``--data``): the adapter
reads ``ratings.csv`` (ml-20m/25m header format
``userId,movieId,rating,timestamp``) or ``ratings.dat``
(ml-1m/ml-10m ``::``-separated) and returns the same COO index-space
arrays the synthesizer produces.

Reference anchor: the reference's docs benchmark ALS on MovieLens-
shaped CSV through the same input-line codec the batch layer ingests
(docs/docs/performance.html; MLFunctions.PARSE_FN).
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["load_movielens", "movielens_or_synthetic"]


def load_movielens(path: str):
    """(users, items, values, user_ids, item_ids) from a MovieLens
    directory or ratings file.  Users/items are re-indexed densely;
    ``values`` are the raw star ratings (float32)."""
    if os.path.isdir(path):
        for name in ("ratings.csv", "ratings.dat"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no ratings.csv/ratings.dat under {path}")
    # loadtxt's C tokenizer: ml-20m's 20M rows parse in seconds where
    # genfromtxt's Python line loop takes minutes and GBs
    if path.endswith(".dat"):
        with open(path) as f:
            raw = np.loadtxt((ln.replace("::", ",") for ln in f),
                             delimiter=",", dtype=np.float64,
                             usecols=(0, 1, 2), ndmin=2)
    else:
        raw = np.loadtxt(path, delimiter=",", skiprows=1,
                         dtype=np.float64, usecols=(0, 1, 2), ndmin=2)
    user_raw = raw[:, 0].astype(np.int64)
    item_raw = raw[:, 1].astype(np.int64)
    values = raw[:, 2].astype(np.float32)
    uniq_u, users = np.unique(user_raw, return_inverse=True)
    uniq_i, items = np.unique(item_raw, return_inverse=True)
    return (users.astype(np.int32), items.astype(np.int32), values,
            [str(u) for u in uniq_u.tolist()],
            [str(i) for i in uniq_i.tolist()])


def movielens_or_synthetic(data_path: str | None, n_ratings: int,
                           seed: int = 7, n_users: int | None = None,
                           n_items: int | None = None):
    """(users, items, explicit_values, user_ids, item_ids, source).

    ``data_path`` (or $ORYX_ML_DATA) selects the real files; otherwise
    the planted-structure synthesizer at MovieLens-20M shape (or a
    smaller ``n_users`` x ``n_items`` space for sub-scale runs — a
    tiny rating count over the full 138k-user space leaves the
    time-split's test users unseen in training)."""
    data_path = data_path or os.environ.get("ORYX_ML_DATA")
    if data_path:
        users, items, values, user_ids, item_ids = load_movielens(data_path)
        return users, items, values, user_ids, item_ids, data_path
    from .train import ML20M_ITEMS, ML20M_USERS, synthesize_movielens

    users, items, _, exp_vals, _ = synthesize_movielens(
        n_users=n_users or ML20M_USERS, n_items=n_items or ML20M_ITEMS,
        n_ratings=n_ratings, seed=seed)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1
    return (users, items, exp_vals,
            [str(u) for u in range(n_users)],
            [str(i) for i in range(n_items)],
            f"synthetic-ml20m-shape({n_ratings} ratings)")
