"""The FULL published serving grid, over live HTTP.

The reference publishes a 12-row `/recommend` envelope — features in
{50, 250} x items in {1M, 5M, 20M} x LSH {off, on(0.3)} — with qps and
p-latency at 1-3 concurrent requests on a 32-core Haswell Xeon
(docs/docs/performance.html; BASELINE.md).  Round-2 proved exactly one
cell (50f/1M exact).  This harness serves EVERY cell through the real
stack (stdlib HTTP server, route dispatch, request micro-batcher,
streaming/flat device kernels) and records, per row:

  - saturating throughput (many concurrent keep-alive clients), and
  - p50 latency at LOW concurrency (2 workers, the reference's regime),

plus the measured device round-trip floor of this environment's TPU
tunnel: the chip here sits behind a network transport whose ~100 ms
round trip dominates single-request latency, so low-concurrency p50
carries the floor alongside for honest comparison (a locally attached
TPU pays ~1 ms for the same dispatch).

Factor storage is bfloat16 across the grid — the config that makes the
largest row (20M items x 250 features = 10 GB + user side) fit one
chip's HBM, mirroring the reference's 25.8 GB heap row on partitioned
maps (PartitionedFeatureVectors.java:43-222).

Usage: python -m oryx_tpu.bench.grid [--items 1,5,20] [--features 50,250]
Writes one JSON object (the full table) to stdout; the driver-facing
single-line headline stays in bench.py.
"""

from __future__ import annotations

import argparse
import gc
import json
import threading
import time

import numpy as np

# (features, items_millions, lsh) -> (qps, p_lat_ms) from BASELINE.md
BASELINES = {
    (50, 1, False): (70, 28), (250, 1, False): (24, 40),
    (50, 5, False): (16, 57), (250, 5, False): (6, 181),
    (50, 20, False): (4, 257), (250, 20, False): (1, 668),
    (50, 1, True): (437, 7), (250, 1, True): (160, 12),
    (50, 5, True): (91, 21), (250, 5, True): (37, 54),
    (50, 20, True): (25, 79), (250, 20, True): (7, 134),
}

N_USERS = 10_000
TOP_N = 10
# 512 concurrent keep-alive clients: the serving loop is CLOSED-LOOP —
# each worker waits its own response, so qps <= workers / end-to-end
# latency, and through a ~110 ms tunnel 256 workers cap out near
# 256/0.2s ~= 1,280 qps regardless of device or host headroom (the
# host path alone measured 8.8k req/s with an instant scorer).  512
# measured best on this 1-core host; 768+ thrashes.
SAT_WORKERS = 512
LOW_WORKERS = 2
LOW_REQUESTS = 60
MEASURE_SEC = 15.0
MAX_BATCH = 1024
# batch size for the kernel-only probe — the serving streaming window
_CHUNKED_BATCH_PROBE = 256


def measure_tunnel_floor() -> float:
    """Median ms for one tiny dispatch + fetch — the transport's
    per-request latency floor, independent of model size."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a):
        return a + 1.0

    a = jnp.zeros((8, 8), jnp.float32)
    jax.device_get(f(a))
    times = []
    for _ in range(7):
        t0 = time.perf_counter()
        jax.device_get(f(a))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def build_model(features: int, items: int, rng):
    """Synthetic serving model at grid scale, loaded through the same
    bulk path MODEL publish uses; bf16 rows generated slab-wise so host
    peak memory stays ~1 slab above the resident matrix."""
    import ml_dtypes

    from ..app.als.serving_model import ALSServingModel

    model = ALSServingModel(features, implicit=True, sample_rate=0.3,
                            dtype="bfloat16")
    ids = [str(i) for i in range(items)]
    Y = np.empty((items, features), dtype=ml_dtypes.bfloat16)
    slab = 2_000_000
    for s in range(0, items, slab):
        e = min(s + slab, items)
        Y[s:e] = rng.standard_normal((e - s, features)).astype(
            ml_dtypes.bfloat16)
    model.Y.bulk_load(ids, Y)
    del Y
    user_ids = [f"u{u}" for u in range(N_USERS)]
    X = rng.standard_normal((N_USERS, features)).astype(np.float32)
    model.X.bulk_load(user_ids, X)
    model.Y.device_arrays()  # upload outside any timed region
    return model, user_ids


def device_bytes(model) -> int:
    caps = len(model.Y.row_ids()) + len(model.X.row_ids())
    return caps * model.features * model.Y.dtype.itemsize


def descend_until_sustained(base: str, user_ids, rates, ladder: list,
                            *, duration_sec: float, workers: int,
                            how_many: int) -> None:
    """Append open-loop rungs at descending ``rates`` to ``ladder``
    until one sustains — used when no ascending rung held, so a cell
    reports a measured sustained rate instead of 0.0.  Rates are
    deduped (a qps floor can collapse several multipliers onto the
    same value) and rates already attempted in ``ladder`` are
    skipped."""
    from .load import run_recommend_open_loop

    seen = {o["offered_qps"] for o in ladder}
    for rate in dict.fromkeys(round(r, 1) for r in rates):
        if rate in seen:
            continue
        o = run_recommend_open_loop(base, user_ids, rate_qps=rate,
                                    duration_sec=duration_sec,
                                    workers=workers, how_many=how_many)
        ladder.append(o)
        if o["sustained"]:
            return


def bench_config(features: int, items_m: int, model, user_ids,
                 host_cap_qps: float | None = None,
                 peaks: dict | None = None) -> list[dict]:
    from ..lambda_rt.http import HttpApp, make_server
    from ..serving import als as als_resources
    from ..serving import framework as framework_resources
    from ..serving.batcher import TopNBatcher
    from .load import (StaticModelManager, run_recommend_load,
                       run_recommend_open_loop)

    StaticModelManager.model = model
    rows = []
    lsh_obj = model.lsh
    for lsh_on in (False, True):
        model.lsh = lsh_obj if lsh_on else None
        # each in-flight streaming dispatch holds a (256, chunk) score
        # tile; cap concurrency at 20M items so tiles + the 10 GB bf16
        # item matrix stay inside one chip's HBM
        depth = 16 if items_m >= 20 else 32
        batcher = TopNBatcher(max_batch=MAX_BATCH, pipeline=depth)
        app = HttpApp(
            framework_resources.ROUTES + als_resources.ROUTES,
            context={"model_manager": StaticModelManager(),
                     "input_producer": None, "config": None,
                     "min_model_load_fraction": 0.0,
                     "top_n_batcher": batcher},
            read_only=True)
        server = make_server(app, 0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{port}"
        fallbacks_at_start = model.twophase_fallbacks
        try:
            # compile warm-up: every pow2 drain-size bucket the batcher
            # can produce at the load driver's how_many (same top_k
            # width -> the warmed kernels ARE the measured kernels),
            # plus the certificate-failure fallback scan
            model.warm_serving_kernels(TOP_N, MAX_BATCH)
            # kernel-only exec time, tunnel excluded (VERDICT r3: no
            # artifact could split device time from tunnel/batching),
            # now with the per-pass roofline decomposition (ISSUE 3)
            from .kernel_probe import probe_model
            probe = probe_model(model, batch=_CHUNKED_BATCH_PROBE, m=4,
                                peaks=peaks)
            # calibrate: short timed burst sets the request count so the
            # measured run lasts ~MEASURE_SEC
            cal = run_recommend_load(base, user_ids,
                                     requests=SAT_WORKERS * 4,
                                     workers=SAT_WORKERS, how_many=TOP_N)
            n_req = max(512, int(cal.qps * MEASURE_SEC))
            sat = run_recommend_load(base, user_ids, requests=n_req,
                                     workers=SAT_WORKERS, how_many=TOP_N)
            # OPEN-LOOP rate ladder (reference: TrafficUtil.java:63
            # exponential inter-arrival): the closed-loop number above
            # is bounded by workers/RTT through the device tunnel; the
            # open-loop run offers a fixed arrival rate and measures
            # whether the server sustains it, latency counted from the
            # scheduled arrival.  Rungs are MULTIPLES of the measured
            # closed-loop qps: sustaining >1x demonstrates the server
            # is not the closed-loop binding constraint.  The client
            # thread pool shares this 1-core host, so the highest
            # honest rung is bounded by client capacity too —
            # server_capacity_est_qps (min of the stub-scorer host
            # loopback and this cell's kernel ceiling) is the
            # client-independent decomposition.
            open_loop = []
            for mult in (1.0, 1.5, 2.0):
                rate = max(50.0, sat.qps * mult)
                open_loop.append(run_recommend_open_loop(
                    base, user_ids, rate_qps=rate, duration_sec=6.0,
                    workers=SAT_WORKERS, how_many=TOP_N))
                if not open_loop[-1]["sustained"]:
                    break
            if not any(o["sustained"] for o in open_loop):
                # the closed-loop rate itself wasn't sustainable (the
                # tunnel RTT lets a closed-loop client briefly exceed
                # steady-state capacity); descend until a rung holds
                descend_until_sustained(
                    base, user_ids,
                    [max(25.0, sat.qps * m) for m in (0.7, 0.5, 0.35,
                                                      0.25)],
                    open_loop, duration_sec=6.0, workers=SAT_WORKERS,
                    how_many=TOP_N)
            sustained = [o["offered_qps"] for o in open_loop
                         if o["sustained"]]
            open_loop_capacity = max(sustained) if sustained else 0.0
            # snapshot drain/pacing state NOW, while it reflects the
            # saturation run (the unloaded probes below would pollute
            # the recent-batch window with 1-3 request drains)
            batcher_stats = batcher.stats()
            sizes = batcher.batch_sizes[-2000:]
            batcher_stats["mean_batch_all"] = round(
                sum(sizes) / max(1, len(sizes)), 1)
            # UNLOADED latency at the reference's 1-3 concurrency (the
            # baseline's p-lat regime): idle server, per worker count.
            # The tunnel floor is re-measured HERE, contemporaneously:
            # the run-start floor can drift +-30 ms over a 50-minute
            # grid, which dominated the p50-minus-floor column.
            cell_floor = measure_tunnel_floor()
            unloaded = {}
            for w in (1, 2, 3):
                lw = run_recommend_load(base, user_ids,
                                        requests=LOW_REQUESTS * w,
                                        workers=w, how_many=TOP_N)
                unloaded[w] = {"p50_ms": round(lw.percentile_ms(50), 1),
                               "p95_ms": round(lw.percentile_ms(95), 1)}
            low = unloaded[LOW_WORKERS]
        finally:
            server.shutdown()
            batcher.close()
        base_qps, base_lat = BASELINES.get((features, items_m, lsh_on),
                                           (None, None))
        # the ROUTED path is the served path: map the measured-cost
        # router's chosen kind onto the probe's timing key, falling
        # back to the static preference order when no route measured
        route = probe.get("kernel_route") or {}
        kernel_path = {
            "i8_fold": "twophase_pallas_i8_fold",
            "fold": "twophase_pallas_fold",
            "i8": "twophase_pallas_i8",
            "pallas": "twophase_pallas",
            "scan": "twophase",
        }.get(route.get("chosen"), route.get("chosen"))
        if kernel_path not in probe:
            kernel_path = next((p for p in
                                ("twophase_pallas_i8_fold",
                                 "twophase_pallas_fold",
                                 "twophase_pallas_i8",
                                 "twophase_pallas",
                                 "twophase", "flat_lsh", "flat",
                                 "chunked_exact") if p in probe), None)
        kern = probe.get(kernel_path, {})
        rows.append({
            "features": features,
            "items": round(items_m * 1_000_000),
            "lsh": lsh_on,
            "qps": round(sat.qps, 1),
            "qps_errors": sat.errors,
            # closed-loop qps above is tunnel-bound (workers/RTT); the
            # open-loop rows measure the SERVER at offered arrival
            # rates (TrafficUtil-style), and open_loop_sustained_qps is
            # the highest offered rate whose mid-window completion
            # throughput reached >=95% of it without backlog divergence
            "open_loop": open_loop,
            "open_loop_sustained_qps": open_loop_capacity,
            # client-independent server capacity: the host path with an
            # instant scorer x this cell's device kernel ceiling
            "server_capacity_est_qps": round(min(
                host_cap_qps or float("inf"),
                kern.get("qps_ceiling") or float("inf")), 1)
            if (host_cap_qps or kern.get("qps_ceiling")) else None,
            "p50_ms_at_2_workers": low["p50_ms"],
            "p95_ms_saturated": round(sat.percentile_ms(95), 1),
            "unloaded_latency_ms": unloaded,
            "device_exec_ms": None if kern.get("unmeasurable")
            else kern.get("exec_ms"),
            "device_exec_batch": probe.get("batch"),
            "effective_gb_per_s": kern.get("effective_gb_per_s"),
            "kernel_qps_ceiling": kern.get("qps_ceiling"),
            "kernel_path": kernel_path,
            # per-pass roofline decomposition of the served path plus
            # the full per-path probe and the measured-cost route —
            # the reviewer-checkable evidence for "at a physical bound
            # or not" (ISSUE 3 / VERDICT r5 Weak #2)
            "roofline": kern.get("roofline"),
            "kernel_probe": {p: probe[p] for p in
                             ("twophase", "twophase_pallas",
                              "twophase_pallas_fold",
                              "twophase_pallas_i8",
                              "twophase_pallas_i8_fold",
                              "chunked_exact", "phase_b_only",
                              "phase_b_only_i8width",
                              "flat", "flat_lsh") if p in probe},
            "kernel_route": probe.get("kernel_route"),
            "lsh_routed_effective": (probe.get("kernel_route") or {}
                                     ).get("use_lsh"),
            "baseline_qps": base_qps,
            "baseline_p_lat_ms": base_lat,
            "vs_baseline_qps": round(sat.qps / base_qps, 2)
            if base_qps else None,
            "tunnel_floor_at_cell_ms": round(cell_floor, 1),
            "p50_minus_tunnel_floor_ms": round(
                low["p50_ms"] - cell_floor, 1),
            "device_mb": round(device_bytes(model) / 1e6, 1),
            "batcher": batcher_stats,
            # exact-scan recomputes forced by failed two-phase
            # certificates during THIS cell's run (delta against the
            # cumulative model counter; expected 0)
            "twophase_fallbacks": model.twophase_fallbacks
            - fallbacks_at_start,
        })
        print(json.dumps(rows[-1]), flush=True)
    model.lsh = lsh_obj
    # drop the class-attribute reference NOW: it otherwise keeps this
    # cell's device arrays (canonical + fold mirror) alive while the
    # next config uploads its own matrix — 50f/20M (7.7 GB with the
    # mirror) still resident under the 250f/20M build (10 GB) is a
    # measured HBM OOM
    StaticModelManager.model = None
    return rows


def host_loopback_capacity() -> dict:
    """The serving host path with the device taken out: a stub scorer
    answers instantly, so closed-loop 512-worker qps and an open-loop
    ladder measure HTTP parse + route + batcher + JSON encode on this
    host alone.  Server capacity for a cell is then
    min(host_loopback, that cell's kernel ceiling) — the decomposition
    that separates server capacity from tunnel-bound closed-loop qps."""
    from ..lambda_rt.http import HttpApp, make_server
    from ..serving import als as als_resources
    from ..serving import framework as framework_resources
    from .load import (StaticModelManager, run_recommend_load,
                       run_recommend_open_loop)

    from ..app.als.serving_model import ALSServingModel

    class StubModel(ALSServingModel):
        # passes the route's isinstance gate but never touches a
        # device: every method the /recommend path calls is overridden
        features = 8
        rescorer_provider = None
        _result = [(f"i{j}", 1.0 - j / 100.0) for j in range(TOP_N)]

        def __init__(self):  # noqa: D401 — no stores, no jax
            pass

        def get_fraction_loaded(self):
            return 1.0

        def get_user_vector(self, _id):
            return np.zeros(8, np.float32)

        def get_known_items(self, _id):
            return set()

        def top_n(self, how_many, **_kw):
            return self._result[:how_many]

        def top_n_batch(self, how_many, vectors, exclude=None,
                        use_lsh=True):
            hm = [how_many] * len(vectors) \
                if isinstance(how_many, int) else how_many
            return [self._result[:h] for h in hm]

    StaticModelManager.model = StubModel()
    app = HttpApp(
        framework_resources.ROUTES + als_resources.ROUTES,
        context={"model_manager": StaticModelManager(),
                 "input_producer": None, "config": None,
                 "min_model_load_fraction": 0.0,
                 "top_n_batcher": None},
        read_only=True)
    server = make_server(app, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    user_ids = [f"u{i}" for i in range(256)]
    try:
        closed = run_recommend_load(base, user_ids, requests=20_000,
                                    workers=64, how_many=TOP_N)
        rate, sustained = closed.qps, []
        ladder = []
        for frac in (0.5, 0.75, 0.9):
            o = run_recommend_open_loop(base, user_ids,
                                        rate_qps=rate * frac,
                                        duration_sec=5.0, workers=128,
                                        how_many=TOP_N)
            ladder.append(o)
            if o["sustained"]:
                sustained.append(o["offered_qps"])
        if not sustained:
            descend_until_sustained(
                base, user_ids, [rate * f for f in (0.35, 0.25, 0.15)],
                ladder, duration_sec=5.0, workers=128, how_many=TOP_N)
            sustained = [o["offered_qps"] for o in ladder
                         if o["sustained"]]
    finally:
        server.shutdown()
    return {
        "closed_loop_qps": round(closed.qps, 1),
        "open_loop": ladder,
        "open_loop_sustained_qps": max(sustained) if sustained else 0.0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", default="1,5,20")
    ap.add_argument("--features", default="50,250")
    ap.add_argument("--out", default=None,
                    help="write the grid artifact JSON here")
    ap.add_argument("--lat-out", default=None,
                    help="write the unloaded-latency artifact here")
    args = ap.parse_args()
    # fractional --items (e.g. 0.6) runs off-envelope scales — used for
    # CPU-backend smoke/regression runs; baseline columns go None there
    items_list = [int(float(x)) if float(x) == int(float(x))
                  else float(x) for x in args.items.split(",")]
    features_list = [int(x) for x in args.features.split(",")]

    floor = measure_tunnel_floor()
    print(json.dumps({"tunnel_floor_ms": round(floor, 1)}), flush=True)
    from .kernel_probe import measure_peaks
    peaks = measure_peaks()
    print(json.dumps({"peaks": peaks}), flush=True)
    host_cap = host_loopback_capacity()
    print(json.dumps({"host_loopback": host_cap}), flush=True)
    all_rows = []
    for items_m in items_list:
        for features in features_list:
            rng = np.random.default_rng(round(items_m * 1000) + features)
            t0 = time.time()
            model, user_ids = build_model(features,
                                          round(items_m * 1_000_000),
                                          rng)
            print(json.dumps({"built": f"{features}f/{items_m}M",
                              "sec": round(time.time() - t0, 1)}), flush=True)
            all_rows.extend(bench_config(
                features, items_m, model, user_ids,
                host_cap_qps=host_cap.get("open_loop_sustained_qps"),
                peaks=peaks))
            del model
            gc.collect()
    import jax

    grid_doc = {
        "metric": "als_recommend_http_grid",
        # backend identity gates round-over-round comparison
        # (bench/check_regression.py refuses cross-backend diffs)
        "backend": jax.default_backend(),
        "tunnel_floor_ms": round(floor, 1),
        "peaks": peaks,
        "host_loopback": host_cap,
        # HEADLINE summary leads with open-loop SUSTAINED qps (the
        # arrival-driven number, TrafficUtil semantics); closed-loop is
        # the secondary column — at the largest scales it is tunnel-
        # bound and overstates what the server holds under offered load
        "summary": [
            {"config": f"{r['features']}f/"
                       f"{r['items'] / 1_000_000:g}M"
                       f"{'/lsh' if r['lsh'] else ''}",
             "sustained_qps": r["open_loop_sustained_qps"],
             "closed_loop_qps": r["qps"],
             "vs_baseline_sustained": round(
                 r["open_loop_sustained_qps"] / r["baseline_qps"], 2)
             if r["baseline_qps"] else None}
            for r in all_rows
        ],
        "headline_metric": "open_loop_sustained_qps",
        "rows": all_rows,
        "note": ("HEADLINE: summary[].sustained_qps — highest offered "
                 "arrival rate each cell held (open-loop, exponential "
                 "inter-arrival; latency from scheduled arrival). "
                 "Closed-loop qps is secondary: bounded by workers/RTT "
                 "through the device tunnel. "
                 "unloaded_latency_ms: idle server, 1-3 workers (the "
                 "baseline's concurrency regime), measured after the "
                 "saturation run drained. device_exec_ms: kernel-only "
                 "time from an m-deep dispatch queue, tunnel excluded. "
                 "p50 decomposes as tunnel_floor + device_exec + host. "
                 "Baselines: docs/docs/performance.html, 32-core "
                 "Haswell, 1-3 concurrent requests."),
    }
    print(json.dumps(grid_doc))
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(grid_doc) + "\n")
    if args.lat_out:
        lat_doc = {
            "metric": "als_recommend_unloaded_latency",
            "tunnel_floor_ms": round(floor, 1),
            "rows": [{k: r[k] for k in
                      ("features", "items", "lsh", "unloaded_latency_ms",
                       "device_exec_ms", "device_exec_batch",
                       "kernel_path", "baseline_p_lat_ms")}
                     for r in all_rows],
            "note": ("Idle server, 1/2/3 workers, keep-alive raw-socket "
                     "clients; p50 = tunnel_floor + device_exec/"
                     "effective_batch + host. The tunnel's ~100 ms "
                     "round trip dominates every cell here; a locally "
                     "attached chip pays ~1 ms for the same dispatch "
                     "(device_exec_ms is the measured on-chip part)."),
        }
        with open(args.lat_out, "w") as f:
            f.write(json.dumps(lat_doc) + "\n")


if __name__ == "__main__":
    main()
