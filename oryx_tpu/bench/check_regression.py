"""CI guard: fail when the newest serving bench round regresses on
sustained throughput (ISSUE 3 satellite; gateway cells ISSUE 4;
observability overhead ISSUE 7).

Three artifact families share the machinery, selected by ``--kind``:

- ``grid`` (default): ``BENCH_GRID_*.json``, cells keyed by
  (features, items, lsh) — the single-node serving envelope.
- ``gateway``: ``BENCH_GATEWAY_*.json``, cells keyed by
  (features, items, replicas, replicas-per-shard) — the
  scatter-gather cluster's per-topology scaling rounds (R-way
  replica-group cells gate independently of their R=1 siblings;
  pre-r09 artifacts are all R=1).  Since r11 a row's hot-user Zipf
  rung gates as its own ``(..., "zipf")`` pseudo-cell — a
  result-cache regression cannot hide behind a healthy cold cell,
  and pre-cache artifacts simply lack the cell.  Since r12 a row's
  per-replica model-load telemetry (sharded model distribution,
  ISSUE 10) gates as the ``(..., "load")`` pseudo-cell on LOAD SPEED
  (1 / max replica ``model_load_s``), with the same
  lacking-cell-is-new back-compat.  Since r13 the ``--regions 2``
  mirror probe (ISSUE 11) gates as the ``(..., "mirror")``
  pseudo-cell on healed-partition catch-up speed (records/s), same
  back-compat.  Since r14 the connection-count rung (ISSUE 12, C10K
  front end) gates as the ``(..., "conns")`` pseudo-cell on qps
  sustained through the top rung's concurrent sockets, same
  back-compat.  Since r15 the write-heavy rung (ISSUE 17,
  ``--write-heavy``) gates as the ``(..., "writes")`` pseudo-cell on
  sustained ACKED writes/s through the durable-ack ingest path, same
  back-compat.  Also since r15 the IVF-ANN rung (ISSUE 18, ``--ann``)
  gates as the ``(..., "ann")`` pseudo-cell on the ANN door's
  sustained qps at the large-catalog cell (recall certificate and
  speedup-vs-exact ride along), same back-compat.
- ``obs``: ``BENCH_OBS_OVERHEAD_*.json`` — the observability
  hot-path microbench (bench/obs_overhead.py).  Gates on two rules:
  a HARD absolute budget (the unsampled per-request pipeline must
  stay under 10 µs — the standing single-digit-µs contract from
  docs/OBSERVABILITY.md) and a relative creep gate between
  same-backend rounds (default threshold 50% for this kind:
  nanosecond microbenches are box-noise-sensitive where qps cells
  are not, and the absolute budget is the real contract).  Since r16
  the budget gates ``unsampled_recorder_armed`` — the full pipeline
  with the flight recorder's rings fed (ISSUE 20), the worst
  unsampled cell — falling back to ``unsampled_full_pipeline`` for
  pre-r16 artifacts, which simply lack the cell in the relative
  gate.

Joins the two most recent rounds (by round number in the filename) on
the cell key and exits non-zero when any cell's HEADLINE metric —
``open_loop_sustained_qps``, the arrival-driven number the summaries
lead with — dropped by more than ``--threshold`` (default 10%).
Closed-loop qps and device_exec_ms are reported alongside for
diagnosis but do not gate (they are tunnel- and backend-sensitive).

Artifacts from different backends (a CPU smoke round vs a TPU round)
are never compared: the guard reports the skip and exits 0 — a silent
cross-backend "regression" would train people to ignore the gate.

Usage:
    python -m oryx_tpu.bench.check_regression [--kind grid|gateway|obs]
        [--dir .] [--threshold 0.10] [--current F] [--previous F]
Exit codes: 0 ok/skip, 1 regression, 2 usage/artifact error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

__all__ = ["compare_grids", "compare_obs", "find_grid_artifacts",
           "find_gateway_artifacts", "find_obs_artifacts", "main"]

_GRID_RE = re.compile(r"BENCH_GRID(?:20M)?_r(\d+)([a-z]?)\.json$")
_GATEWAY_RE = re.compile(r"BENCH_GATEWAY_r(\d+)([a-z]?)\.json$")
_OBS_RE = re.compile(r"BENCH_OBS_OVERHEAD_r(\d+)([a-z]?)\.json$")

# the unsampled obs pipeline's hard budget (ns/request): single-digit
# microseconds, docs/OBSERVABILITY.md "Tracing overhead"
OBS_BUDGET_NS = 10_000


def _find_artifacts(directory: str, pattern: re.Pattern) -> list[str]:
    found = []
    for name in os.listdir(directory):
        m = pattern.match(name)
        if m:
            found.append((int(m.group(1)), m.group(2),
                          os.path.join(directory, name)))
    return [p for _, _, p in sorted(found)]


def find_grid_artifacts(directory: str) -> list[str]:
    """Grid artifact paths sorted oldest-to-newest by (round, suffix)."""
    return _find_artifacts(directory, _GRID_RE)


def find_gateway_artifacts(directory: str) -> list[str]:
    return _find_artifacts(directory, _GATEWAY_RE)


def find_obs_artifacts(directory: str) -> list[str]:
    return _find_artifacts(directory, _OBS_RE)


def compare_obs(prev: dict, cur: dict, threshold: float = 0.50,
                budget_ns: int = OBS_BUDGET_NS) -> dict:
    """Obs-overhead comparison: the absolute per-request budget gates
    unconditionally; the relative gate compares only keys both rounds
    measured (r08 predates ``unsampled_full_pipeline``)."""
    report: dict = {"regressions": [], "improved": [], "ok": [],
                    "skipped": None, "budget_ns": budget_ns}
    if not backends_comparable(prev.get("backend"), cur.get("backend")):
        report["skipped"] = (
            f"backend mismatch: previous={prev.get('backend')} "
            f"current={cur.get('backend')} — cross-backend ns is not "
            f"a regression signal")
        # the absolute budget still applies to the current round
        prev = {"microbench_ns_per_request": {}}
    p = prev.get("microbench_ns_per_request") or {}
    c = cur.get("microbench_ns_per_request") or {}
    # the budget gates the WORST unsampled cell the round measured:
    # recorder-armed (r16) > full pipeline (r10) > tracer-only (r08)
    hot = c.get("unsampled_recorder_armed",
                c.get("unsampled_full_pipeline",
                      c.get("unsampled_begin_branch_current")))
    if hot is None:
        report["regressions"].append(
            {"cell": "unsampled hot path",
             "error": "current round measured no unsampled ns"})
        return report
    if hot > budget_ns:
        report["regressions"].append(
            {"cell": "unsampled hot path", "ns_cur": hot,
             "over_budget_ns": budget_ns,
             "detail": "single-digit-µs contract broken"})
    for key in ("unsampled_begin_branch_current",
                "unsampled_full_pipeline",
                "unsampled_recorder_armed"):
        if key not in p or key not in c:
            continue
        old, new = float(p[key]), float(c[key])
        cell = {"cell": key, "ns_prev": old, "ns_cur": new}
        if old <= 0:
            report["ok"].append(cell)
            continue
        cell["ratio"] = round(new / old, 3)
        if new > old * (1.0 + threshold):
            report["regressions"].append(cell)
        elif new < old * (1.0 - threshold):
            report["improved"].append(cell)
        else:
            report["ok"].append(cell)
    return report


def _cells(doc: dict) -> dict:
    if doc.get("metric") == "gateway_recommend_scaling":
        # per-replica-count scaling cells (bench/gateway.py); the
        # replica-group size R joined the key in r09 — pre-elastic
        # rounds are all R=1, so they keep gating the R=1 cells.
        # r11 added the hot-user Zipf rung: it gates as its own
        # pseudo-cell (base key + "zipf") so a result-cache
        # regression cannot hide behind a healthy cold cell — and
        # pre-cache artifacts simply lack the cell (reported new,
        # never compared)
        out = {}
        for r in doc.get("rows", []):
            key = (r["features"], r["items"], r["replicas"],
                   r.get("replicas_per_shard", 1))
            out[key] = r
            z = r.get("zipf")
            if isinstance(z, dict) \
                    and z.get("open_loop_sustained_qps") is not None:
                out[key + ("zipf",)] = z
            # r12 added per-replica model-load telemetry (sharded model
            # distribution): it gates as its own (..., "load")
            # pseudo-cell whose headline is LOAD SPEED — 1 /
            # max-replica model_load_s, so a >10% drop in the gated
            # number means load time rose >11% (a slice-load
            # regression cannot hide behind a healthy qps cell).
            # Pre-r12 artifacts simply lack the cell.
            load = r.get("model_load")
            if isinstance(load, dict) \
                    and load.get("max_replica_load_s"):
                out[key + ("load",)] = {
                    "open_loop_sustained_qps": round(
                        1.0 / load["max_replica_load_s"], 4),
                    "model_load_s": load["max_replica_load_s"],
                    "mode": load.get("mode"),
                }
            # ISSUE 11 added the two-region mirror probe (`--regions
            # 2`): it gates as its own (..., "mirror") pseudo-cell
            # whose headline is healed-partition CATCH-UP SPEED
            # (records replayed per second after the link returns), so
            # a mirror-throughput regression cannot hide behind a
            # healthy qps cell; steady-state staleness rides along for
            # diagnosis.  Pre-region artifacts simply lack the cell.
            mir = r.get("mirror")
            if isinstance(mir, dict) \
                    and mir.get("catch_up_records_per_s"):
                out[key + ("mirror",)] = {
                    "open_loop_sustained_qps":
                        mir["catch_up_records_per_s"],
                    "catch_up_s": mir.get("catch_up_s"),
                    "steady_staleness_ms":
                        mir.get("steady_staleness_ms"),
                }
            # r14 added the connection-count rung (C10K front end,
            # ISSUE 12): it gates as its own (..., "conns")
            # pseudo-cell on the qps sustained THROUGH the top rung's
            # concurrent sockets, so a front-end regression (the
            # event loop losing throughput at high connection counts,
            # or errors appearing — errors zero the gated number)
            # cannot hide behind a healthy low-concurrency cell.
            # Socket and router-thread telemetry ride along for
            # diagnosis.  Pre-r14 artifacts simply lack the cell.
            conns = r.get("conns")
            if isinstance(conns, dict) \
                    and conns.get("open_loop_sustained_qps") \
                    is not None:
                out[key + ("conns",)] = {
                    "open_loop_sustained_qps":
                        conns["open_loop_sustained_qps"],
                    "connections": conns.get("connections"),
                    "router_threads_at_load":
                        conns.get("router_threads_at_load"),
                    "hit_p50_ms": conns.get("hit_p50_ms"),
                }
            # ISSUE 17 added the write-heavy rung (`--write-heavy`):
            # it gates as its own (..., "writes") pseudo-cell on the
            # highest sustained ACKED writes/s through the durable-ack
            # ingest path (serving door -> input topic -> speed
            # fold-in), so a write-path regression — gate, pipelined
            # produce, or broker append — cannot hide behind a healthy
            # read cell.  The acked==durable ledger and fold-in
            # freshness ride along for diagnosis.  Pre-r15 artifacts
            # simply lack the cell.
            w = r.get("writes")
            if isinstance(w, dict) \
                    and w.get("open_loop_sustained_qps") is not None:
                out[key + ("writes",)] = {
                    "open_loop_sustained_qps":
                        w["open_loop_sustained_qps"],
                    "acked_equals_durable":
                        w.get("acked_equals_durable"),
                    "ingest_to_servable_ms":
                        w.get("ingest_to_servable_ms"),
                    "p50_shed_ms":
                        (w.get("overload") or {}).get("p50_shed_ms"),
                }
            # ISSUE 18 added the IVF-ANN rung (`--ann`): it gates as
            # its own (..., "ann") pseudo-cell on the ANN door's
            # sustained qps at the probe's large-catalog cell, so an
            # index-build or routing regression (ANN silently failing
            # closed to the exact kernel serves correctly but at
            # exact-kernel speed — the gated number collapses) cannot
            # hide behind the healthy small-catalog cells.  The recall
            # certificate, the speedup over the exact door on the SAME
            # generation, and p99 ride along for diagnosis.  Pre-r15
            # artifacts simply lack the cell.
            a = r.get("ann")
            if isinstance(a, dict) \
                    and a.get("open_loop_sustained_qps") is not None:
                out[key + ("ann",)] = {
                    "open_loop_sustained_qps":
                        a["open_loop_sustained_qps"],
                    "speedup_vs_exact": a.get("speedup_vs_exact"),
                    "recall": (a.get("certificate") or {}).get("recall"),
                    "sustained_p99_ms": a.get("sustained_p99_ms"),
                }
        return out
    return {(r["features"], r["items"], r["lsh"]): r
            for r in doc.get("rows", [])}


def _cell_label(doc: dict, key: tuple) -> str:
    if doc.get("metric") == "gateway_recommend_scaling":
        label = f"{key[0]}f/{key[1] / 1e6:g}M/{key[2]}rep"
        if key[3] != 1:
            label += f"x{key[3]}"
        if len(key) > 4:
            label += f"/{key[4]}"
        return label
    return f"{key[0]}f/{key[1] / 1e6:g}M{'/lsh' if key[2] else ''}"


# backend names the TPU-tunnel envelope reports under (plain jax and
# the remote-plugin stack); legacy artifacts are only comparable to
# these, never to e.g. a gpu round that merely isn't cpu
_TPU_BACKENDS = ("tpu", "axon")


def backends_comparable(prev_backend, cur_backend) -> bool:
    """Whether two rounds' qps numbers are a regression signal.  A
    missing backend field marks a pre-r06 artifact: those rounds
    (r01-r05) all ran the TPU-tunnel envelope, so they stay comparable
    to a TPU-backend current round — otherwise the gate would silently
    skip the very first gated TPU round after this field was
    introduced.  Every other pairing must match exactly."""
    if prev_backend == cur_backend:
        return True
    return prev_backend is None and cur_backend in _TPU_BACKENDS


def compare_grids(prev: dict, cur: dict,
                  threshold: float = 0.10) -> dict:
    """Cell-by-cell comparison report; ``report["regressions"]`` is the
    gating list."""
    report: dict = {"regressions": [], "improved": [], "ok": [],
                    "missing_cells": [], "new_cells": [],
                    "skipped": None}
    prev_backend = prev.get("backend")
    cur_backend = cur.get("backend")
    if not backends_comparable(prev_backend, cur_backend):
        report["skipped"] = (
            f"backend mismatch: previous={prev_backend} "
            f"current={cur_backend} — cross-backend qps is not a "
            f"regression signal")
        return report
    pc, cc = _cells(prev), _cells(cur)
    report["missing_cells"] = sorted(str(k) for k in pc if k not in cc)
    report["new_cells"] = sorted(str(k) for k in cc if k not in pc)
    for key in sorted(k for k in pc if k in cc):
        p, c = pc[key], cc[key]
        old = p.get("open_loop_sustained_qps") or 0.0
        new = c.get("open_loop_sustained_qps") or 0.0
        cell = {
            "cell": _cell_label(cur, key),
            "sustained_qps_prev": old,
            "sustained_qps_cur": new,
            "closed_loop_prev": p.get("qps"),
            "closed_loop_cur": c.get("qps"),
            "device_exec_ms_prev": p.get("device_exec_ms"),
            "device_exec_ms_cur": c.get("device_exec_ms"),
        }
        if old <= 0.0:
            # nothing sustained last round: any measurement is progress
            report["ok"].append(cell)
            continue
        ratio = new / old
        cell["ratio"] = round(ratio, 3)
        if ratio < 1.0 - threshold:
            report["regressions"].append(cell)
        elif ratio > 1.0 + threshold:
            report["improved"].append(cell)
        else:
            report["ok"].append(cell)
    return report


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kind", choices=("grid", "gateway", "obs"),
                    default="grid",
                    help="artifact family: single-node serving grid, "
                         "the cluster gateway's per-replica scaling, "
                         "or the observability overhead microbench")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_*_r*.json rounds")
    ap.add_argument("--threshold", type=float, default=None,
                    help="relative regression gate (default 0.10; "
                         "0.50 for --kind obs, where the absolute "
                         "budget is the real contract)")
    ap.add_argument("--current", default=None,
                    help="explicit current artifact (else newest)")
    ap.add_argument("--previous", default=None,
                    help="explicit previous artifact (else second-newest)")
    args = ap.parse_args(argv)
    if args.threshold is None:
        args.threshold = 0.50 if args.kind == "obs" else 0.10

    def _load(path):
        with open(path) as f:
            return json.load(f)

    skipped_rounds: list[str] = []
    if args.current and args.previous:
        cur_path, prev_path = args.current, args.previous
        try:
            cur, prev = _load(cur_path), _load(prev_path)
        except (OSError, json.JSONDecodeError) as e:
            print(json.dumps({"error": f"unreadable artifact: {e}"}))
            return 2
    else:
        finders = {"gateway": find_gateway_artifacts,
                   "obs": find_obs_artifacts,
                   "grid": find_grid_artifacts}
        arts = finders[args.kind](args.dir)
        if args.current:
            cur_path = args.current
            arts = [a for a in arts
                    if os.path.abspath(a) != os.path.abspath(cur_path)]
        elif arts:
            cur_path = arts.pop()
        else:
            kind = {"gateway": "GATEWAY", "obs": "OBS_OVERHEAD",
                    "grid": "GRID"}[args.kind]
            print(json.dumps({"error": f"no BENCH_{kind}_*.json found"}))
            return 2
        try:
            cur = _load(cur_path)
        except (OSError, json.JSONDecodeError) as e:
            print(json.dumps({"error": f"unreadable artifact: {e}"}))
            return 2
        if args.previous:
            prev_path = args.previous
            try:
                prev = _load(prev_path)
            except (OSError, json.JSONDecodeError) as e:
                print(json.dumps({"error": f"unreadable artifact: {e}"}))
                return 2
        else:
            # walk back to the NEWEST artifact on the same backend: a
            # CPU smoke round committed between two TPU rounds must not
            # un-gate the TPU sequence (the TPU r07 compares against
            # TPU r05, skipping the cpu r06 in between)
            prev_path = prev = None
            for cand in reversed(arts):
                try:
                    doc = _load(cand)
                except (OSError, json.JSONDecodeError):
                    skipped_rounds.append(os.path.basename(cand))
                    continue
                if backends_comparable(doc.get("backend"),
                                       cur.get("backend")):
                    prev_path, prev = cand, doc
                    break
                skipped_rounds.append(os.path.basename(cand))
            if prev is None:
                if args.kind == "obs":
                    # no relative comparison possible, but the HARD
                    # absolute budget is unconditional — a first round
                    # (or first round on a new backend) is exactly
                    # where a budget break is most likely
                    report = compare_obs(
                        {"backend": cur.get("backend"),
                         "microbench_ns_per_request": {}},
                        cur, threshold=args.threshold)
                    report["skipped"] = ("no prior obs round on "
                                        f"backend {cur.get('backend')!r}"
                                        " — absolute budget only")
                    report["skipped_rounds"] = skipped_rounds
                    report["current"] = os.path.basename(cur_path)
                    print(json.dumps(report, indent=1))
                    return 1 if report["regressions"] else 0
                print(json.dumps({
                    "skipped": f"no prior {args.kind} round on backend "
                               f"{cur.get('backend')!r}",
                    "skipped_rounds": skipped_rounds,
                    "current": os.path.basename(cur_path)}))
                return 0
    compare = compare_obs if args.kind == "obs" else compare_grids
    report = compare(prev, cur, threshold=args.threshold)
    report["previous"] = os.path.basename(prev_path)
    report["current"] = os.path.basename(cur_path)
    report["threshold"] = args.threshold
    if skipped_rounds:
        # rounds between current and the chosen base that were not
        # comparable (other backend / unreadable) — visible so a gap in
        # the gated sequence is never silent
        report["skipped_rounds"] = skipped_rounds
    print(json.dumps(report, indent=1))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
