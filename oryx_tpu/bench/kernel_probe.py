"""Device-exec timing for the serving scan kernels, tunnel-excluded.

VERDICT r03: no artifact records kernel-only time for the grid cells,
so device inefficiency, batching loss and tunnel latency cannot be told
apart.  This probe isolates device execution on a transport where
``block_until_ready`` is a no-op and a single dispatch+fetch pays a
~100 ms round trip: it times one dispatch+fetch (rtt + exec) and a
back-to-back queue of ``m`` dispatches fetched once (rtt + m*exec; the
chip executes queued programs in order), and reports the difference.

    exec = (t_m - t_1) / (m - 1)

Also derives effective HBM scan bandwidth (bytes of item matrix per
exec) — the number to compare against the chip's spec to decide whether
a cell is bandwidth-bound or overhead-bound.

ISSUE 3 adds the ROOFLINE layer (Williams et al., CACM 2009): the probe
now also measures the chip's own ceilings (streaming HBM bandwidth and
per-dtype matmul peak, by the same m-queue estimator) and decomposes
every kernel path per PASS — phase B is timed standalone over synthetic
block maxima and subtracted from the full program, and each pass gets
analytic bytes-moved / flops alongside its measured time, so achieved
GB/s, achieved TFLOP/s, HBM fraction and an MXU-occupancy estimate are
reviewer-checkable numbers, not assertions.

Usage: python -m oryx_tpu.bench.kernel_probe --items 20 --features 250
       [--lsh off|on|both] [--batch 256] [--peaks]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["probe_model", "time_exec", "measure_peaks"]


def time_exec(dispatch, fetch, m: int = 6, reps: int = 3,
              min_delta_ms: float = 30.0, max_m: int = 96) -> dict:
    """Median (rtt+exec) of one dispatch+fetch, and per-exec time from
    an ``m``-deep dispatch queue.  ``dispatch()`` must enqueue one
    device program and return its output handle(s) without blocking;
    ``fetch(h)`` must block until that handle's program completed.

    Small kernels (exec ≪ tunnel-RTT jitter) would make the m-queue
    delta indistinguishable from noise — and occasionally negative — so
    the queue is deepened until the delta clears ``min_delta_ms``."""
    fetch(dispatch())  # ensure compiled
    while True:
        t1s, tms = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            fetch(dispatch())
            t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            hs = [dispatch() for _ in range(m)]
            fetch(hs[-1])
            tms.append(time.perf_counter() - t0)
        t1 = float(np.median(t1s))
        tm = float(np.median(tms))
        if (tm - t1) * 1e3 >= min_delta_ms or m >= max_m:
            break
        m = min(max_m, m * 4)
    return {
        "t1_ms": round(t1 * 1e3, 1),
        "tm_ms": round(tm * 1e3, 1),
        "m": m,
        "exec_ms": round((tm - t1) / (m - 1) * 1e3, 3),
    }


def measure_peaks(m: int = 6) -> dict:
    """The chip's own roofline ceilings, measured with the same m-queue
    estimator the kernel timings use so the ratios cancel transport
    effects: streaming HBM bandwidth (a big copy; bytes = read+write)
    and matmul peak per MXU dtype path (f32, bf16-in/f32-acc,
    int8-in/int32-acc).  Shapes scale down on the CPU backend so the
    probe stays runnable in tier-1-adjacent smoke tests."""
    import jax
    import jax.numpy as jnp

    cpu = jax.default_backend() == "cpu"
    copy_elems = (1 << 24) if cpu else (1 << 28)      # 64 MB / 1 GB f32
    n_mm = 512 if cpu else 4096

    @jax.jit
    def copy_k(a):
        return a + 1.0

    a = jnp.zeros((copy_elems,), jnp.float32)
    t = time_exec(lambda: copy_k(a), jax.device_get, m=m)
    peaks = {
        "copy_mb": round(copy_elems * 4 / 1e6, 1),
        "hbm_gb_per_s": None if t["exec_ms"] <= 0 else round(
            2 * copy_elems * 4 / t["exec_ms"] / 1e6, 1),
        "matmul_n": n_mm,
    }
    from functools import partial

    @partial(jax.jit, static_argnames=("out",))
    def mm(x, y, out):
        return jax.lax.dot_general(
            x, y, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32 if out == "i32"
            else jnp.float32)

    rng = np.random.default_rng(5)
    base = rng.standard_normal((n_mm, n_mm)).astype(np.float32)
    for name, dt, out in (("f32", jnp.float32, "f32"),
                          ("bf16", jnp.bfloat16, "f32"),
                          ("int8", jnp.int8, "i32")):
        try:
            if name == "int8":
                x = jnp.asarray(
                    np.clip(base * 20, -127, 127).astype(np.int8))
            else:
                x = jnp.asarray(base).astype(dt)
            t = time_exec(lambda: mm(x, x, out), jax.device_get, m=m)
            peaks[f"matmul_{name}_tflops"] = None if t["exec_ms"] <= 0 \
                else round(2 * n_mm ** 3 / t["exec_ms"] / 1e9, 2)
        except Exception as e:  # noqa: BLE001 — backend-dependent dtypes
            peaks[f"matmul_{name}_tflops"] = None
            peaks[f"matmul_{name}_error"] = str(e)[:120]
    return peaks


def _phase_decomposition(name: str, timing: dict, *, vecs, buckets,
                         n_rows: int, B: int, bs: int, ksel: int,
                         fold: int, itemsize: int, peaks: dict | None,
                         phase_b_ms: dict | None) -> None:
    """Attach the per-pass roofline record to a timed path: analytic
    bytes/flops per pass, the measured phase split, and (with peaks)
    achieved-vs-ceiling ratios.  Phase-A bytes count what each path's
    mirror actually streams — this is the decomposition that says
    whether a cell is at a physical bound or leaving bandwidth on the
    table (VERDICT r5 Weak #2)."""
    if timing.get("unmeasurable") or timing["exec_ms"] <= 0:
        return
    W = int(vecs.shape[1])
    n_blocks = n_rows // bs
    lsh = buckets is not None
    mirror_bytes = {
        "twophase_pallas": n_rows * W * itemsize,
        "twophase_pallas_fold": n_rows * W * itemsize // max(1, fold),
        "twophase_pallas_i8": n_rows * W,
        "twophase_pallas_i8_fold": n_rows * W // max(1, fold),
        "twophase": n_rows * W * itemsize,
        "chunked_exact": n_rows * W * itemsize,
        "flat": n_rows * W * itemsize,
        "flat_lsh": n_rows * W * itemsize,
    }.get(name)
    if mirror_bytes is None:
        return
    pa_bytes = mirror_bytes + n_blocks * B * 4  # + block-maxima out
    if lsh:
        # the folded bucket side input is a RELAYOUT of all N int32
        # ids ((fold, N//bs, bs//fold) = N elements), not fold-reduced
        pa_bytes += n_rows * 4
    if name == "twophase":
        # the lax.scan build spills each (B, chunk) score tile to HBM
        # and reads it back for the block max — the F-independent tax
        # the pallas build exists to avoid
        pa_bytes += 2 * B * n_rows * 4
    if name in ("flat", "flat_lsh"):
        pa_bytes += B * n_rows * 4  # materialized (B, N) scores
    pa_flops = 2 * B * n_rows * W
    dtype_key = "int8" if "i8" in name else (
        "bf16" if itemsize == 2 else "f32")
    roof: dict = {
        "phase_a_bytes": pa_bytes,
        "phase_a_flops": pa_flops,
        "mxu_dtype": dtype_key,
    }
    # the int8 paths run phase B at the widened _i8_ksel selection
    # width (buys back the bound margin's false-failure rate), so both
    # the analytic bytes/flops and the subtracted measured phase-B
    # time must use that width — one record, one program
    from ..app.als import serving_model as sm

    ksel_eff = sm._i8_ksel(ksel, n_rows, bs) if "i8" in name else ksel
    if name.startswith("twophase"):
        # single-pass paths (chunked_exact, flat) have no phase B
        roof["phase_b_bytes"] = \
            B * ksel_eff * bs * W * itemsize + B * n_blocks * 4
        roof["phase_b_flops"] = 2 * B * ksel_eff * bs * W
    exec_ms = timing["exec_ms"]
    pb_ms = (phase_b_ms or {}).get(ksel_eff)
    if pb_ms is not None and 0 < pb_ms < exec_ms \
            and name.startswith(("twophase",)):
        pa_ms = exec_ms - pb_ms
        roof["phase_b_ms"] = round(pb_ms, 3)
        roof["phase_a_ms"] = round(pa_ms, 3)
        roof["phase_a_gb_per_s"] = round(pa_bytes / pa_ms / 1e6, 1)
        roof["phase_a_tflops"] = round(pa_flops / pa_ms / 1e9, 3)
    else:
        # no split available: attribute the whole program to phase A
        # (flat kernels have no phase B; a failed split is flagged)
        roof["phase_a_ms"] = round(exec_ms, 3)
        roof["phase_a_gb_per_s"] = round(pa_bytes / exec_ms / 1e6, 1)
        roof["phase_a_tflops"] = round(pa_flops / exec_ms / 1e9, 3)
        if name.startswith("twophase"):
            roof["phase_split_unavailable"] = True
    if peaks:
        peak_bw = peaks.get("hbm_gb_per_s")
        peak_fl = peaks.get(f"matmul_{dtype_key}_tflops")
        if peak_bw:
            roof["hbm_fraction"] = round(
                roof["phase_a_gb_per_s"] / peak_bw, 3)
        if peak_fl:
            roof["mxu_occupancy_est"] = round(
                roof["phase_a_tflops"] / peak_fl, 3)
    timing["roofline"] = roof


def probe_model(model, batch: int = 256, how_many: int = 10,
                m: int = 6, probe_int8: bool | None = None,
                peaks: dict | None = None) -> dict:
    """Time the exact device programs the serving path dispatches for a
    ``batch``-query drain on ``model``, excluding host and tunnel.
    ``probe_int8`` (default: the model's own int8 enablement) times the
    int8 block-selection phase-A builds — unfolded and, where the shape
    folds, the int8+fold mirror — and records their certificate-failure
    counts.  ``peaks`` (from :func:`measure_peaks`) turns each path's
    decomposition into achieved-vs-ceiling ratios."""
    import jax
    import jax.numpy as jnp

    from ..app.als import serving_model as sm

    if probe_int8 is None:
        probe_int8 = model._int8_enabled()
    vecs, active, version = model.Y.device_arrays_versioned()
    n_rows = int(vecs.shape[0])
    k = min(sm._pad_k(how_many), n_rows)
    big, chunk = sm._stream_plan(n_rows, batch)
    rng = np.random.default_rng(0)
    Q = jnp.asarray(rng.standard_normal(
        (batch, model.features)).astype(np.float32))
    lsh_on = model._lsh_active()
    buckets = model._cached_buckets(vecs, version) if lsh_on else None
    hp = model.lsh._device_hyperplanes() if lsh_on else None
    mb = model.lsh.max_bits_differing if lsh_on else 0
    scan_bytes = n_rows * model.features * vecs.dtype.itemsize
    itemsize = vecs.dtype.itemsize

    out: dict = {
        "items": n_rows, "features": model.features,
        "batch": batch, "k": k, "lsh": lsh_on,
        "streaming": bool(big), "chunk": chunk,
        "scan_mb": round(scan_bytes / 1e6, 1),
    }
    route = getattr(model, "_route", None)
    if route is not None:
        out["kernel_route"] = route

    bs = sm._BLOCK_ROWS
    ksel = min(sm._BLOCK_KSEL, n_rows // max(1, bs))
    fold = sm._fold_eligible(int(vecs.shape[1]), model.features, bs) \
        if model._fold_enabled() else 1
    # standalone phase-B time PER SELECTION WIDTH: the int8 paths run
    # the doubled _i8_ksel width, so their subtraction needs its own
    # measurement
    phase_b_ms: dict = {}

    def add(name, timing, bytes_scanned=None):
        if timing["exec_ms"] <= 0:
            # tunnel jitter swallowed the m-queue delta (small kernels:
            # m*exec inside the ~100 ms RTT variance) — flag rather
            # than emit absurd derived numbers
            timing["unmeasurable"] = True
            timing["effective_gb_per_s"] = None
            timing["qps_ceiling"] = None
        else:
            timing["effective_gb_per_s"] = round(
                (bytes_scanned or scan_bytes) / timing["exec_ms"] / 1e6,
                1)
            timing["qps_ceiling"] = round(
                batch / timing["exec_ms"] * 1e3, 1)
        _phase_decomposition(
            name, timing, vecs=vecs, buckets=buckets, n_rows=n_rows,
            B=batch, bs=bs, ksel=ksel, fold=fold, itemsize=itemsize,
            peaks=peaks, phase_b_ms=phase_b_ms)
        out[name] = timing

    if big and n_rows % chunk == 0 and k <= chunk:
        if n_rows % bs == 0 and 1 <= ksel < n_rows // bs and k <= ksel * bs:
            # phase B standalone over synthetic block maxima (its cost
            # is value-independent: same approx_max_k + gather +
            # einsum), so every two-phase path's full time decomposes
            # into measured phase A + measured phase B — timed at each
            # selection width in use
            M = jnp.asarray(rng.standard_normal(
                (batch, n_rows // bs)).astype(np.float32))
            widths = {ksel}
            if probe_int8:
                widths.add(sm._i8_ksel(ksel, n_rows, bs))
            for w_sel in sorted(widths):
                try:
                    tb = time_exec(
                        lambda: sm._phase_b_only(vecs, Q, active,
                                                 buckets, hp, M, k, bs,
                                                 w_sel, mb),
                        jax.device_get, m=m)
                    if tb["exec_ms"] > 0:
                        phase_b_ms[w_sel] = tb["exec_ms"]
                        key = "phase_b_only" if w_sel == ksel \
                            else "phase_b_only_i8width"
                        out[key] = tb
                except Exception as e:  # noqa: BLE001
                    out["phase_b_only_error"] = str(e)[:160]
            add("twophase", time_exec(
                lambda: sm._batch_top_n_twophase_kernel(
                    vecs, Q, active, buckets, hp, k, chunk, bs, ksel, mb),
                jax.device_get, m=m))
            if n_rows % sm._PA_TILE == 0:
                penalty = model._cached_penalty(active, version)
                try:
                    add("twophase_pallas", time_exec(
                        lambda: sm._batch_top_n_twophase_pallas(
                            vecs, Q, penalty, active, buckets, hp, k,
                            bs, ksel, mb),
                        jax.device_get, m=m))
                except Exception as e:  # noqa: BLE001 — backend-dependent
                    out["twophase_pallas_error"] = str(e)[:160]
                if fold > 1:
                    try:
                        yf, pen_f, bkt_f = model._cached_fold(
                            vecs, active, buckets, version, fold, bs)
                        add("twophase_pallas_fold", time_exec(
                            lambda: sm._batch_top_n_twophase_pallas_fold(
                                vecs, yf, Q, pen_f, active, bkt_f,
                                buckets, hp, k, bs, ksel, mb, fold),
                            jax.device_get, m=m),
                            # phase A streams the folded mirror
                            bytes_scanned=scan_bytes
                            * vecs.shape[1] // model.features // fold)
                    except Exception as e:  # noqa: BLE001
                        out["twophase_pallas_fold_error"] = str(e)[:160]
                if probe_int8:
                    ksel_i8 = sm._i8_ksel(ksel, n_rows, bs)
                    try:
                        y8, sy_b, l1y_b = model._cached_i8(vecs, version)
                        penalty_i = model._cached_penalty_i(active,
                                                            version)
                        t = time_exec(
                            lambda: sm._batch_top_n_twophase_pallas_i8(
                                vecs, y8, sy_b, l1y_b, Q, penalty_i,
                                active, buckets, hp, k, bs, ksel_i8, mb),
                            jax.device_get, m=m)
                        # certificate pass rate at this ksel matters as
                        # much as speed: every failed row recomputes on
                        # the exact scan
                        _, _, cert = jax.device_get(
                            sm._batch_top_n_twophase_pallas_i8(
                                vecs, y8, sy_b, l1y_b, Q, penalty_i,
                                active, buckets, hp, k, bs, ksel_i8, mb))
                        t["cert_fail_rows"] = int((~cert).sum())
                        # int8 phase A streams the 1 B/elem Y8 mirror,
                        # which is lane-padded like the store
                        add("twophase_pallas_i8", t,
                            bytes_scanned=n_rows * int(vecs.shape[1]))
                    except Exception as e:  # noqa: BLE001
                        out["twophase_pallas_i8_error"] = str(e)[:160]
                    if fold > 1:
                        try:
                            y8f, pen_i_f, bkt_f, sy_b, l1y_b = \
                                model._cached_i8_fold(vecs, active,
                                                      buckets, version,
                                                      fold, bs)
                            t = time_exec(
                                lambda:
                                sm._batch_top_n_twophase_pallas_i8_fold(
                                    vecs, y8f, sy_b, l1y_b, Q, pen_i_f,
                                    active, bkt_f, buckets, hp, k, bs,
                                    ksel_i8, mb, fold),
                                jax.device_get, m=m)
                            _, _, cert = jax.device_get(
                                sm._batch_top_n_twophase_pallas_i8_fold(
                                    vecs, y8f, sy_b, l1y_b, Q, pen_i_f,
                                    active, bkt_f, buckets, hp, k, bs,
                                    ksel_i8, mb, fold))
                            t["cert_fail_rows"] = int((~cert).sum())
                            # int8+fold phase A streams 1 B/elem over
                            # width/fold lanes: ~items x features bytes
                            add("twophase_pallas_i8_fold", t,
                                bytes_scanned=n_rows
                                * int(vecs.shape[1]) // fold)
                        except Exception as e:  # noqa: BLE001
                            out["twophase_pallas_i8_fold_error"] = \
                                str(e)[:160]
        add("chunked_exact", time_exec(
            lambda: sm._batch_top_n_chunked_kernel(
                vecs, Q, active, buckets, hp, k, chunk, mb),
            jax.device_get, m=m))
    else:
        if lsh_on:
            add("flat_lsh", time_exec(
                lambda: sm._batch_top_n_lsh_kernel(
                    vecs, Q, active, buckets, hp, k, mb),
                jax.device_get, m=m))
        else:
            add("flat", time_exec(
                lambda: sm._batch_top_n_kernel(vecs, Q, active, k),
                jax.device_get, m=m))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=float, default=20.0,
                    help="millions of items")
    ap.add_argument("--features", type=int, default=250)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--lsh", choices=["off", "on", "both"],
                    default="off")
    ap.add_argument("--m", type=int, default=6)
    ap.add_argument("--int8", action="store_true",
                    help="probe the int8 phase-A builds even when the "
                         "model's int8-selection would not use them")
    ap.add_argument("--no-int8", action="store_true",
                    help="skip the int8 probes even where "
                         "int8-selection enables them (the pre-int8 "
                         "comparison run)")
    ap.add_argument("--no-peaks", action="store_true",
                    help="skip the roofline-ceiling measurement")
    args = ap.parse_args()

    from .grid import build_model

    peaks = None
    if not args.no_peaks:
        peaks = measure_peaks(m=args.m)
        print(json.dumps({"peaks": peaks}), flush=True)
    rng = np.random.default_rng(7)
    model, _ = build_model(args.features, int(args.items * 1e6), rng)
    lsh_obj = model.lsh
    if args.lsh in ("off", "both"):
        model.lsh = None
        print(json.dumps(probe_model(model, batch=args.batch, m=args.m,
                                     probe_int8=True if args.int8 else (False if args.no_int8 else None),
                                     peaks=peaks)),
              flush=True)
    if args.lsh in ("on", "both"):
        model.lsh = lsh_obj
        print(json.dumps(probe_model(model, batch=args.batch, m=args.m,
                                     probe_int8=True if args.int8 else (False if args.no_int8 else None),
                                     peaks=peaks)),
              flush=True)


if __name__ == "__main__":
    main()
