"""Device-exec timing for the serving scan kernels, tunnel-excluded.

VERDICT r03: no artifact records kernel-only time for the grid cells,
so device inefficiency, batching loss and tunnel latency cannot be told
apart.  This probe isolates device execution on a transport where
``block_until_ready`` is a no-op and a single dispatch+fetch pays a
~100 ms round trip: it times one dispatch+fetch (rtt + exec) and a
back-to-back queue of ``m`` dispatches fetched once (rtt + m*exec; the
chip executes queued programs in order), and reports the difference.

    exec = (t_m - t_1) / (m - 1)

Also derives effective HBM scan bandwidth (bytes of item matrix per
exec) — the number to compare against the chip's spec to decide whether
a cell is bandwidth-bound or overhead-bound.

Usage: python -m oryx_tpu.bench.kernel_probe --items 20 --features 250
       [--lsh off|on|both] [--batch 256]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["probe_model", "time_exec"]


def time_exec(dispatch, fetch, m: int = 6, reps: int = 3,
              min_delta_ms: float = 30.0, max_m: int = 96) -> dict:
    """Median (rtt+exec) of one dispatch+fetch, and per-exec time from
    an ``m``-deep dispatch queue.  ``dispatch()`` must enqueue one
    device program and return its output handle(s) without blocking;
    ``fetch(h)`` must block until that handle's program completed.

    Small kernels (exec ≪ tunnel-RTT jitter) would make the m-queue
    delta indistinguishable from noise — and occasionally negative — so
    the queue is deepened until the delta clears ``min_delta_ms``."""
    fetch(dispatch())  # ensure compiled
    while True:
        t1s, tms = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            fetch(dispatch())
            t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            hs = [dispatch() for _ in range(m)]
            fetch(hs[-1])
            tms.append(time.perf_counter() - t0)
        t1 = float(np.median(t1s))
        tm = float(np.median(tms))
        if (tm - t1) * 1e3 >= min_delta_ms or m >= max_m:
            break
        m = min(max_m, m * 4)
    return {
        "t1_ms": round(t1 * 1e3, 1),
        "tm_ms": round(tm * 1e3, 1),
        "m": m,
        "exec_ms": round((tm - t1) / (m - 1) * 1e3, 3),
    }


def probe_model(model, batch: int = 256, how_many: int = 10,
                m: int = 6, probe_int8: bool = False) -> dict:
    """Time the exact device programs the serving path dispatches for a
    ``batch``-query drain on ``model``, excluding host and tunnel.
    ``probe_int8`` additionally times the int8 block-selection phase A
    (regardless of the model's int8-selection setting) and records its
    certificate-failure count."""
    import jax
    import jax.numpy as jnp

    from ..app.als import serving_model as sm

    vecs, active, version = model.Y.device_arrays_versioned()
    n_rows = int(vecs.shape[0])
    k = min(sm._pad_k(how_many), n_rows)
    big, chunk = sm._stream_plan(n_rows, batch)
    rng = np.random.default_rng(0)
    Q = jnp.asarray(rng.standard_normal(
        (batch, model.features)).astype(np.float32))
    lsh_on = model._lsh_active()
    buckets = model._cached_buckets(vecs, version) if lsh_on else None
    hp = model.lsh._device_hyperplanes() if lsh_on else None
    mb = model.lsh.max_bits_differing if lsh_on else 0
    scan_bytes = n_rows * model.features * vecs.dtype.itemsize

    out: dict = {
        "items": n_rows, "features": model.features,
        "batch": batch, "k": k, "lsh": lsh_on,
        "streaming": bool(big), "chunk": chunk,
        "scan_mb": round(scan_bytes / 1e6, 1),
    }

    def add(name, timing, bytes_scanned=None):
        if timing["exec_ms"] <= 0:
            # tunnel jitter swallowed the m-queue delta (small kernels:
            # m*exec inside the ~100 ms RTT variance) — flag rather
            # than emit absurd derived numbers
            timing["unmeasurable"] = True
            timing["effective_gb_per_s"] = None
            timing["qps_ceiling"] = None
        else:
            timing["effective_gb_per_s"] = round(
                (bytes_scanned or scan_bytes) / timing["exec_ms"] / 1e6,
                1)
            timing["qps_ceiling"] = round(
                batch / timing["exec_ms"] * 1e3, 1)
        out[name] = timing

    if big and n_rows % chunk == 0 and k <= chunk:
        bs = sm._BLOCK_ROWS
        ksel = min(sm._BLOCK_KSEL, n_rows // max(1, bs))
        if n_rows % bs == 0 and 1 <= ksel < n_rows // bs and k <= ksel * bs:
            add("twophase", time_exec(
                lambda: sm._batch_top_n_twophase_kernel(
                    vecs, Q, active, buckets, hp, k, chunk, bs, ksel, mb),
                jax.device_get, m=m))
            if n_rows % sm._PA_TILE == 0:
                penalty = model._cached_penalty(active, version)
                try:
                    add("twophase_pallas", time_exec(
                        lambda: sm._batch_top_n_twophase_pallas(
                            vecs, Q, penalty, active, buckets, hp, k,
                            bs, ksel, mb),
                        jax.device_get, m=m))
                except Exception as e:  # noqa: BLE001 — backend-dependent
                    out["twophase_pallas_error"] = str(e)[:160]
                fold = sm._fold_eligible(int(vecs.shape[1]),
                                         model.features, bs) \
                    if model._fold_enabled() else 1
                if fold > 1:
                    try:
                        yf, pen_f, bkt_f = model._cached_fold(
                            vecs, active, buckets, version, fold, bs)
                        add("twophase_pallas_fold", time_exec(
                            lambda: sm._batch_top_n_twophase_pallas_fold(
                                vecs, yf, Q, pen_f, active, bkt_f,
                                buckets, hp, k, bs, ksel, mb, fold),
                            jax.device_get, m=m),
                            # phase A streams the folded mirror
                            bytes_scanned=scan_bytes
                            * vecs.shape[1] // model.features // fold)
                    except Exception as e:  # noqa: BLE001
                        out["twophase_pallas_fold_error"] = str(e)[:160]
                if probe_int8:
                    try:
                        y8, sy_b, l1y_b = model._cached_i8(vecs, version)
                        penalty_i = model._cached_penalty_i(active,
                                                            version)
                        ksel_i8 = sm._i8_ksel(ksel, n_rows, bs)
                        t = time_exec(
                            lambda: sm._batch_top_n_twophase_pallas_i8(
                                vecs, y8, sy_b, l1y_b, Q, penalty_i,
                                active, buckets, hp, k, bs, ksel_i8, mb),
                            jax.device_get, m=m)
                        # certificate pass rate at this ksel matters as
                        # much as speed: every failed row recomputes on
                        # the exact scan
                        _, _, cert = jax.device_get(
                            sm._batch_top_n_twophase_pallas_i8(
                                vecs, y8, sy_b, l1y_b, Q, penalty_i,
                                active, buckets, hp, k, bs, ksel_i8, mb))
                        t["cert_fail_rows"] = int((~cert).sum())
                        # int8 phase A streams the 1 B/elem Y8 mirror,
                        # which is lane-padded like the store
                        add("twophase_pallas_i8", t,
                            bytes_scanned=n_rows * int(vecs.shape[1]))
                    except Exception as e:  # noqa: BLE001
                        out["twophase_pallas_i8_error"] = str(e)[:160]
        add("chunked_exact", time_exec(
            lambda: sm._batch_top_n_chunked_kernel(
                vecs, Q, active, buckets, hp, k, chunk, mb),
            jax.device_get, m=m))
    else:
        if lsh_on:
            add("flat_lsh", time_exec(
                lambda: sm._batch_top_n_lsh_kernel(
                    vecs, Q, active, buckets, hp, k, mb),
                jax.device_get, m=m))
        else:
            add("flat", time_exec(
                lambda: sm._batch_top_n_kernel(vecs, Q, active, k),
                jax.device_get, m=m))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=float, default=20.0,
                    help="millions of items")
    ap.add_argument("--features", type=int, default=250)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--lsh", choices=["off", "on", "both"],
                    default="off")
    ap.add_argument("--m", type=int, default=6)
    ap.add_argument("--int8", action="store_true",
                    help="also probe the int8 block-selection phase A")
    args = ap.parse_args()

    from .grid import build_model

    rng = np.random.default_rng(7)
    model, _ = build_model(args.features, int(args.items * 1e6), rng)
    lsh_obj = model.lsh
    if args.lsh in ("off", "both"):
        model.lsh = None
        print(json.dumps(probe_model(model, batch=args.batch, m=args.m,
                                     probe_int8=args.int8)),
              flush=True)
    if args.lsh in ("on", "both"):
        model.lsh = lsh_obj
        print(json.dumps(probe_model(model, batch=args.batch, m=args.m,
                                     probe_int8=args.int8)),
              flush=True)


if __name__ == "__main__":
    main()
