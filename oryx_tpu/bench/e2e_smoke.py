"""Full lambda-loop smoke on REAL hardware, one process.

The CPU test suite proves the loop's logic (tests/test_lambda_it.py);
this drives the same loop — input topic -> BatchLayer generation ->
MODEL/UP on the update topic -> SpeedLayer micro-batch fold-in ->
ServingLayer replay -> live HTTP answers -> /pref write-back — on
whatever device JAX actually has (the TPU, when run without platform
overrides).  It is the "does the whole framework run on the chip"
check, not a benchmark: run it after kernel changes, before recording
artifacts.

Run: python -m oryx_tpu.bench.e2e_smoke
Prints one JSON line with per-stage timings and assertions passed.
"""

from __future__ import annotations

import json
import tempfile
import time
import urllib.request

import numpy as np


def main() -> None:
    import jax

    from ..common.config import from_dict
    from ..kafka.api import KEY_MODEL, KEY_UP
    from ..kafka.inproc import get_broker
    from ..lambda_rt.batch import BatchLayer
    from ..lambda_rt.serving import ServingLayer
    from ..lambda_rt.speed import SpeedLayer

    t_start = time.perf_counter()
    stages: dict[str, float] = {}
    name = f"e2e-{time.monotonic_ns()}"
    with tempfile.TemporaryDirectory() as td:
        cfg = from_dict({
            "oryx.id": "e2e",
            "oryx.input-topic.broker": f"memory://{name}",
            "oryx.input-topic.partitions": 1,
            "oryx.input-topic.message.topic": "In",
            "oryx.update-topic.broker": f"memory://{name}",
            "oryx.update-topic.message.topic": "Up",
            "oryx.batch.update-class": "oryx_tpu.app.als.update.ALSUpdate",
            "oryx.speed.model-manager-class":
                "oryx_tpu.app.als.speed.ALSSpeedModelManager",
            "oryx.serving.model-manager-class":
                "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
            "oryx.serving.application-resources": "oryx_tpu.serving.als",
            "oryx.batch.storage.data-dir": td + "/data",
            "oryx.batch.storage.model-dir": td + "/model",
            # the smoke drives micro-batches manually; park the speed
            # layer's background ticker far out so the manual call is
            # the sole producer over the uncommitted range
            "oryx.speed.streaming.generation-interval-sec": 3600,
            "oryx.als.iterations": 3,
            "oryx.als.implicit": True,
            "oryx.als.hyperparams.features": 8,
            "oryx.ml.eval.test-fraction": 0.0,
        })
        broker = get_broker(name)
        rng = np.random.default_rng(5)
        t = 1_700_000_000_000
        n_in = 0
        for u in range(40):
            for i in range(25):
                if rng.random() < 0.4:
                    broker.send("In", None,
                                f"u{u},i{i},{rng.exponential(1):.2f},{t}")
                    t += 1000
                    n_in += 1

        t0 = time.perf_counter()
        BatchLayer(cfg).run_one_generation()
        stages["batch_generation_s"] = round(time.perf_counter() - t0, 2)
        msgs = list(broker.consume("Up", from_beginning=True,
                                   max_idle_sec=0.3))
        assert msgs and msgs[0].key == KEY_MODEL, "no MODEL published"

        t0 = time.perf_counter()
        speed = SpeedLayer(cfg)
        speed.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                m = speed.model_manager.model
                if m is not None and m.get_fraction_loaded() >= 0.8:
                    break
                time.sleep(0.05)
            before = broker.latest_offset("Up")
            broker.send("In", None, "u0,i1,3.0,1800000000000")
            broker.send("In", None, "brandnew,i2,1.0,1800000000001")
            speed.run_one_micro_batch()
            ups = []
            deadline = time.time() + 30
            while time.time() < deadline:
                after = broker.latest_offset("Up")
                if after > before:
                    ups = [km.message for km in
                           broker.read_range("Up", before, after)
                           if km.key == KEY_UP]
                    if any(json.loads(u)[1] == "brandnew" for u in ups):
                        break
                time.sleep(0.05)
            assert ups, "speed layer produced no UP deltas"
            assert any(json.loads(u)[1] == "brandnew" for u in ups), \
                "fold-in dropped the new user's UP delta"
        finally:
            speed.close()
        stages["speed_fold_in_s"] = round(time.perf_counter() - t0, 2)

        t0 = time.perf_counter()
        serving = ServingLayer(cfg, port=0)
        serving.start()
        try:
            deadline = time.time() + 120
            model = None
            while time.time() < deadline:
                model = serving.model_manager.get_model()
                if model is not None \
                        and model.get_fraction_loaded() >= 0.8:
                    break
                time.sleep(0.05)
            assert model is not None and model.user_count() > 0
            base = f"http://127.0.0.1:{serving.port}"
            uid = model.all_user_ids()[0]
            with urllib.request.urlopen(f"{base}/recommend/{uid}?howMany=4",
                                        timeout=60) as r:
                recs = json.loads(r.read())
            assert len(recs) >= 1 and "id" in recs[0]
            # the speed layer's fold-in reached serving via UP replay
            assert model.get_user_vector("brandnew") is not None, \
                "speed-layer UP delta never reached the serving model"
            with urllib.request.urlopen(f"{base}/similarity/i1?howMany=3",
                                        timeout=60) as r:
                sims = json.loads(r.read())
            assert sims
            # write path: /pref lands on the input topic
            in_before = broker.latest_offset("In")
            req = urllib.request.Request(f"{base}/pref/u0/i3", data=b"4.5",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status in (200, 204)
            tail = broker.read_range("In", in_before,
                                     broker.latest_offset("In"))
            assert any("u0" in m.message and "i3" in m.message
                       for m in tail), "pref never reached the input topic"
        finally:
            serving.close()
        stages["serving_replay_query_s"] = round(time.perf_counter() - t0, 2)

    print(json.dumps({
        "metric": "lambda_e2e_smoke",
        "device": str(jax.devices()[0].platform),
        "input_records": n_in,
        **stages,
        "total_s": round(time.perf_counter() - t_start, 2),
        "ok": True,
    }))


if __name__ == "__main__":
    main()
