"""Fold-in throughput bench: batched vs per-event device dispatch.

SURVEY §7 hard part #2: single-row "UP" updates are batch-hostile on an
accelerator; the reference does one host solve per (user,item) event in
a parallelStream (ALSSpeedModelManager.java:198-220).  The speed layer
batches the whole micro-batch into one kernel (ops/als_fold_in.
fold_in_batch); this bench records events/s for both paths so the
speedup is a number, not a claim.
"""

from __future__ import annotations

import time

import numpy as np

from ..ops import als_fold_in, solver

__all__ = ["run_fold_in_bench"]


def run_fold_in_bench(features: int = 100, events: int = 4096,
                      per_event_sample: int = 64, seed: int = 7,
                      reps: int = 10) -> dict:
    rng = np.random.default_rng(seed)
    y = rng.standard_normal((4 * features, features)).astype(np.float32)
    s = solver.get_solver(y.T @ y)
    values = (rng.exponential(1.0, events) + 0.1).astype(np.float32)
    xu = (rng.standard_normal((events, features)) * 0.2).astype(np.float32)
    yi = rng.standard_normal((events, features)).astype(np.float32)

    # Warm both paths AT THE TIMED SHAPE: the kernel is jitted per
    # pow2 bucket, so warming at batch 8 would leave the timed bucket
    # uncompiled and the measurement compile-dominated (VERDICT r2).
    als_fold_in.fold_in_batch(s, values, xu, yi, implicit=True)
    als_fold_in.compute_updated_xu(s, float(values[0]), xu[0], yi[0], True)

    t0 = time.perf_counter()
    for _ in range(reps):
        new_xu, valid = als_fold_in.fold_in_batch(s, values, xu, yi,
                                                  implicit=True)
    batch_s = (time.perf_counter() - t0) / reps
    # events whose current estimate already exceeds the target fold to
    # "no change" (NaN target) — legitimate, just not counted invalid
    assert np.isfinite(new_xu).all()

    t0 = time.perf_counter()
    for i in range(per_event_sample):
        als_fold_in.compute_updated_xu(s, float(values[i]), xu[i], yi[i],
                                       True)
    per_event_s = (time.perf_counter() - t0) / per_event_sample

    batched_eps = events / batch_s
    single_eps = 1.0 / per_event_s

    # exec-only throughput (tunnel excluded) across batch sizes: time
    # the jitted kernel via an m-deep dispatch queue (kernel_probe) so
    # the ~100 ms transport round trip divides out
    import jax
    import jax.numpy as jnp

    from .kernel_probe import time_exec

    exec_curve = []
    chol_dev = jnp.asarray(s.cholesky)
    for bs in (64, 256, 1024, 4096, 16384):
        vb = jnp.asarray(rng.exponential(1.0, bs).astype(np.float32) + 0.1)
        xb = jnp.asarray(
            (rng.standard_normal((bs, features)) * 0.2).astype(np.float32))
        yb = jnp.asarray(
            rng.standard_normal((bs, features)).astype(np.float32))
        ones = jnp.ones(bs, bool)
        # fold-in kernels are sub-millisecond: the m-queue delta must
        # be deep enough to clear the tunnel's RTT jitter or the
        # subtraction goes negative (observed)
        m = 64 if bs <= 4096 else 16
        t = time_exec(
            lambda: als_fold_in._fold_in_kernel(
                chol_dev, vb, xb, ones, yb, ones, True),
            jax.device_get, m=m, reps=5)
        row = {"batch": bs, "exec_ms": t["exec_ms"]}
        if t["exec_ms"] <= 0:
            row["unmeasurable"] = True
            row["exec_events_per_s"] = None
        else:
            row["exec_events_per_s"] = round(bs / t["exec_ms"] * 1e3, 1)
        exec_curve.append(row)

    # anchor vs the reference's ACTUAL mechanism: one k x k solve per
    # event against the micro-batch's prefactored Cholesky, on a 32-core
    # parallelStream (ALSSpeedModelManager.java:198-220, ALSUtils.java:
    # 74).  Measured here as scipy cho_solve per event on one host core,
    # scaled by the reference box's 32 cores (optimistic for the JVM:
    # zero parallelStream overhead assumed).
    import scipy.linalg as sla

    A = (y.T @ y + 0.01 * np.eye(features)).astype(np.float64)
    cf = sla.cho_factor(A)
    n_host = 2000
    t0 = time.perf_counter()
    for i in range(n_host):
        qui = values[i % events] * yi[i % events]
        sla.cho_solve(cf, qui.astype(np.float64))
    host_per_core_eps = n_host / (time.perf_counter() - t0)
    reference_estimate_eps = host_per_core_eps * 32
    measured = [r for r in exec_curve if r["exec_events_per_s"]]
    best_exec = max((r["exec_events_per_s"] for r in measured),
                    default=None)
    crossover = next((r["batch"] for r in measured
                      if r["exec_events_per_s"] > reference_estimate_eps),
                     None)

    return {
        "exec_only_curve": exec_curve,
        "host_solves_per_core_per_s": round(host_per_core_eps, 1),
        "vs_reference_estimate": {
            "reference_mechanism": "32-core parallelStream of per-event "
                                   "k x k cho_solve against the "
                                   "micro-batch's prefactored Cholesky "
                                   "(ALSSpeedModelManager.java:198-220)",
            "reference_estimate_events_per_s":
                round(reference_estimate_eps, 1),
            "tpu_exec_only_best_events_per_s": best_exec,
            "tpu_wins_from_batch": crossover,
            "ratio_at_best": round(best_exec / reference_estimate_eps, 2)
            if best_exec else None,
        },
        "features": features,
        "events": events,
        "reps": reps,
        "batched_events_per_s": round(batched_eps, 1),
        "per_event_dispatch_events_per_s": round(single_eps, 1),
        "speedup": round(batched_eps / single_eps, 1),
        # context for reading batched_events_per_s: each micro-batch
        # pays one device round trip, so on a tunnel-attached chip the
        # number is transport-bound (batch_s ~= tunnel RTT + upload).
        # The reference's anchor is one 100x100 host Cholesky solve per
        # event on a 32-core parallelStream (ALSUtils.java:74,
        # ALSSpeedModelManager.java:198-220) — roughly 1e4-1e5 solves/s
        # per 32-core box; the batched kernel's device time alone
        # (batch_s minus the round trip) corresponds to >1e6 events/s
        # on a locally attached chip.
        # 6 digits: a locally attached chip's round trip is ~50-200 us,
        # which 4-digit rounding would truncate to 0.0
        "batch_round_trip_s": round(batch_s, 6),
        "tunnel_floor_s": round(_tunnel_floor(), 6),
    }


def _tunnel_floor() -> float:
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a):
        return a + 1.0

    a = jnp.zeros((8, 8), jnp.float32)
    jax.device_get(f(a))
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(f(a))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


if __name__ == "__main__":
    import json
    print(json.dumps(run_fold_in_bench()))
