"""Observability hot-path microbench → BENCH_OBS_OVERHEAD_r*.json.

The standing contract (docs/OBSERVABILITY.md): with every obs feature
COMPILED IN — tracing enabled, exemplar-capable histograms, the SLO
engine's gauges registered, the wide-event log configured — an
UNSAMPLED request must cost single-digit microseconds of observability
work.  This bench measures exactly that composite per-request path:

- ``unsampled_begin_branch_current`` — the r08 tracer-only number
  (begin_request + the thread-current lookup + end_request on the
  shared NOOP_SPAN), kept under the same key so rounds compare;
- ``unsampled_full_pipeline`` — the whole per-request obs tax as the
  dispatcher pays it today: tracer ops + ``MetricsRegistry.record``
  (histogram observe, exemplar branch not taken) + the wide-event
  ``should_emit`` gate (not taken);
- ``unsampled_recorder_armed`` (r16, ISSUE 20) — the full pipeline
  PLUS an armed flight recorder's ``observe_request`` (two ring
  appends, the tick-due comparison, the error-burst branch not
  taken), exactly what the dispatcher pays once ``oryx.obs.flight
  .dir`` is configured — the new budget-gated hot path;
- ``sampled_begin_record_end`` / ``sampled_record_with_exemplar`` —
  the rare sampled request's cost, for scale.

SLO evaluation is deliberately NOT per-request work (it runs at most
once per ``resolution-sec``, triggered by scrapes) — the bench asserts
that by constructing the engine and registering its gauges without
them entering the loop, exactly as the serving tiers wire it.

``check_regression.py --kind obs`` gates successive rounds: the hard
bound is the single-digit-µs budget on the full pipeline; the relative
gate catches creep between same-backend rounds.

Usage:
    python -m oryx_tpu.bench.obs_overhead [--out BENCH_OBS_OVERHEAD_rN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

__all__ = ["run_bench", "main"]


def _ns_per_iter(fn, iterations: int) -> int:
    """Best-of-3 timing (an externally throttled box shows up as two
    slow repeats, not a silently inflated number)."""
    best = None
    for _ in range(3):
        t0 = time.perf_counter_ns()
        fn(iterations)
        dt = (time.perf_counter_ns() - t0) // iterations
        best = dt if best is None else min(best, dt)
    return int(best)


def run_bench(iterations: int = 200_000) -> dict:
    from ..lambda_rt.metrics import MetricsRegistry
    from ..obs.events import WideEventLog
    from ..obs.flight import FlightRecorder
    from ..obs.slo import SloEngine, SloObjective
    from ..obs.trace import Tracer

    # -- tracer-only unsampled path (the r08 measurement, same key) ----------
    t_off = Tracer("bench", sample_ratio=0.0)

    def tracer_unsampled(n):
        for _ in range(n):
            span = t_off.begin_request("bench.request")
            t_off.current()
            t_off.end_request(span, status=200, route="GET /r")

    # -- the full dispatcher pipeline, unsampled -----------------------------
    registry = MetricsRegistry()
    # SLO engine present exactly as a serving tier wires it: gauges
    # registered, evaluation lazy — nothing of it may enter the loop
    engine = SloEngine([SloObjective("availability", "availability",
                                     0.999)], registry)
    registry.gauge_fn("slo_burn_rate", engine.burn_gauge)
    registry.gauge_fn("slo_error_budget_remaining", engine.budget_gauge)
    events_dir = tempfile.mkdtemp(prefix="oryx-obs-bench-")
    events = WideEventLog(events_dir, "bench", registry=registry)

    def full_unsampled(n):
        for _ in range(n):
            span = t_off.begin_request("bench.request")
            t_off.current()
            t_off.end_request(span, status=200, route="GET /r")
            registry.record("GET /r", 200, 0.0042, trace_id=None)
            if events.should_emit(200, 4.2, False):  # pragma: no cover
                events.emit("GET /r", 200, 4.2, None)

    # -- full pipeline + armed flight recorder (r16, ISSUE 20) ---------------
    flight_dir = tempfile.mkdtemp(prefix="oryx-obs-bench-flight-")
    flight = FlightRecorder("bench", registry, dir=flight_dir,
                            dump_on_exit=False)

    def full_recorder_armed(n):
        for _ in range(n):
            span = t_off.begin_request("bench.request")
            t_off.current()
            t_off.end_request(span, status=200, route="GET /r")
            registry.record("GET /r", 200, 0.0042, trace_id=None)
            if events.should_emit(200, 4.2, False):  # pragma: no cover
                events.emit("GET /r", 200, 4.2, None)
            flight.observe_request("GET /r", 200, 4.2)

    # -- sampled costs, for scale --------------------------------------------
    t_on = Tracer("bench", sample_ratio=1.0, max_traces=64)

    def sampled(n):
        for _ in range(n):
            span = t_on.begin_request("bench.request")
            t_on.end_request(span, status=200, route="GET /r")

    reg2 = MetricsRegistry()

    def sampled_record_exemplar(n):
        for _ in range(n):
            reg2.record("GET /r", 200, 0.0042,
                        trace_id="ab" * 16)

    try:
        backend = os.environ.get("JAX_PLATFORMS") or "cpu"
        micro = {
            "unsampled_begin_branch_current":
                _ns_per_iter(tracer_unsampled, iterations),
            "unsampled_full_pipeline":
                _ns_per_iter(full_unsampled, iterations),
            "unsampled_recorder_armed":
                _ns_per_iter(full_recorder_armed, iterations),
            "sampled_begin_record_end":
                _ns_per_iter(sampled, max(1, iterations // 20)),
            "sampled_record_with_exemplar":
                _ns_per_iter(sampled_record_exemplar,
                             max(1, iterations // 20)),
        }
        assert events.emitted == 0, \
            "the unsampled pipeline must never write an event line"
        assert flight.dumps == 0 and flight.dump_failures == 0, \
            "the armed recorder must never dump on the healthy path"
        return {
            "metric": "obs_tracing_overhead",
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "iterations": iterations,
            "note": ("unsampled = tracing enabled + exemplars + SLO "
                     "gauges registered + wide-event log configured, "
                     "request NOT sampled; recorder_armed adds the "
                     "flight recorder's ring appends; best of 3 "
                     "repeats"),
            "microbench_ns_per_request": micro,
        }
    finally:
        flight.close()
        events.close()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact path (BENCH_OBS_OVERHEAD_rN.json)")
    ap.add_argument("--iterations", type=int, default=200_000)
    args = ap.parse_args(argv)
    report = run_bench(iterations=args.iterations)
    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    # the standing budget: single-digit µs per unsampled request —
    # gated on the WORST unsampled cell, the recorder-armed path
    micro = report["microbench_ns_per_request"]
    hot = micro.get("unsampled_recorder_armed",
                    micro["unsampled_full_pipeline"])
    return 0 if hot < 10_000 else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
