"""Cold-start benchmark: process start -> trained generation + first query.

The JVM reference's layers do useful work seconds after exec (deploy/
oryx-batch/src/main/java/com/cloudera/oryx/batch/Main.java — construct,
start, await; nothing to compile).  The TPU runtime pays XLA compilation
instead — BENCH_TRAIN_r03 measured 144 s of first-epoch compile at
MovieLens-20M scale that the JVM never pays.  The persistent compilation
cache (common/compile_cache.py, `oryx.compile-cache-dir`) converts that
to a per-machine cost.  This bench quantifies it end to end:

  parent: fresh cache dir, then an INSTALL-TIME WARMUP (the ``warmup``
          CLI subcommand: one real training iteration at this scale +
          AOT of the resulting serving ladder, all landing in the
          persistent cache — deploy/warmup.py), then TWO child
          processes in sequence —
  child:  enable cache -> synthesize ALS data -> train 2 epochs
          (epoch1 = compile+exec, epoch2 = steady exec) -> build the
          serving model -> warm serving kernels -> first query.

With the warmup stage, run 1 — the FIRST-ever layer start on the
machine — already pays cache loads instead of compilation (ISSUE 3
target: first-ever-cold compile_overhead_s < 60; it was 284 s in r05,
a tax the JVM reference never charges).  Run 2 re-proves the restart
case.  ``--skip-warmup`` restores the old uninstalled-cold
measurement for comparison.

Usage:  python -m oryx_tpu.bench.coldstart [--ratings N --rank K --out F]
One process on the device at a time; never run anything else on the
tunnel concurrently.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

__all__ = ["main"]


def _child(args) -> None:
    import numpy as np

    if args.log_cache:
        import logging

        # compiler logger only: one hit/miss line per compilation
        # (~160 total, negligible timing perturbation) — the dispatch
        # logger would add per-dispatch chatter to a timed run
        logging.basicConfig(level=logging.WARNING)
        logging.getLogger("jax._src.compiler").setLevel(logging.DEBUG)

    t_proc = time.perf_counter()
    from ..common import compile_cache
    from ..common.config import from_dict

    cfg = from_dict({"oryx.compile-cache-dir": args.cache_dir,
                     "oryx.compile-cache-min-compile-secs":
                         args.min_compile_secs})
    compile_cache.enable_from_config(cfg)

    import jax

    jax.devices()  # tunnel/backend contact
    t_backend = time.perf_counter()

    from .train import synthesize_movielens
    from ..app.als.common import ParsedRatings

    users, items, implicit_vals, _, _ = synthesize_movielens(
        n_ratings=args.ratings, seed=11)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1
    ratings = ParsedRatings(
        users=users, items=items, values=implicit_vals,
        user_ids=[f"u{i}" for i in range(n_users)],
        item_ids=[f"i{i}" for i in range(n_items)])
    t_synth = time.perf_counter()

    from ..app.als.trainer import train_als

    epoch_times: list[float] = []
    last = [time.perf_counter()]

    def on_it(i, X, Y):
        now = time.perf_counter()
        epoch_times.append(now - last[0])
        last[0] = now

    model = train_als(ratings, args.rank, lam=0.01, alpha=1.0,
                      implicit=True, iterations=2, seed=3,
                      on_iteration=on_it)
    t_train = time.perf_counter()

    from ..app.als.serving_model import ALSServingModel

    sm = ALSServingModel(features=args.rank, implicit=True)
    sm.Y.bulk_load(ratings.item_ids, model.Y)
    sm.X.bulk_load(ratings.user_ids, model.X)
    sm.warm_serving_kernels(10)
    t_warm = time.perf_counter()
    got = sm.top_n_batch(10, model.X[:2])
    assert len(got) == 2 and got[0]
    t_query = time.perf_counter()

    print(json.dumps({
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "backend_up_s": round(t_backend - t_proc, 2),
        "synth_s": round(t_synth - t_backend, 2),
        "epoch1_s": round(epoch_times[0], 2),
        "epoch2_s": round(epoch_times[1], 2),
        "train_total_s": round(t_train - t_synth, 2),
        "serving_warm_s": round(t_warm - t_train, 2),
        "first_query_s": round(t_query - t_warm, 2),
        # compile cost a restart pays beyond steady-state execution
        "compile_overhead_s": round(
            (epoch_times[0] - epoch_times[1])
            + (t_warm - t_train) + (t_query - t_warm), 2),
    }))


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--ratings", type=int, default=20_000_000)
    p.add_argument("--rank", type=int, default=100)
    p.add_argument("--cache-dir", default=None)
    p.add_argument("--child", action="store_true")
    p.add_argument("--log-cache", action="store_true")
    p.add_argument("--skip-warmup", action="store_true",
                   help="measure the UNinstalled first cold start "
                        "(the pre-ISSUE-3 behavior)")
    p.add_argument("--min-compile-secs", type=float, default=0.5,
                   help="persistence threshold for the compile cache; "
                        "lower it for CPU-scale smoke runs whose "
                        "kernels compile under the production 0.5 s "
                        "gate (they would otherwise never persist and "
                        "the restart leg mis-reads as cache misses)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    if args.child:
        _child(args)
        return

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="oryx-cc-")
    warmup_stats = None
    if not args.skip_warmup:
        # install-time warmup in its own process (its compilations must
        # reach the child through the DISK cache, not process state)
        conf_path = os.path.join(cache_dir, "warmup.conf")
        with open(conf_path, "w") as f:
            f.write('oryx { compile-cache-dir = "%s"\n'
                    '       compile-cache-min-compile-secs = %s }\n'
                    % (cache_dir, args.min_compile_secs))
        cmd = [sys.executable, "-m", "oryx_tpu", "warmup",
               "--conf", conf_path, "--items", "", "--features", "",
               "--train-ratings", str(args.ratings),
               "--train-rank", str(args.rank)]
        t0 = time.perf_counter()
        out = subprocess.run(cmd, capture_output=True, text=True,
                             env=os.environ, check=False)
        wall = round(time.perf_counter() - t0, 2)
        if out.returncode != 0:
            sys.stderr.write(out.stderr)
            raise SystemExit(f"warmup failed rc={out.returncode}")
        warmup_stats = json.loads(out.stdout.strip().splitlines()[-1])
        warmup_stats["process_wall_s"] = wall
    runs = []
    hits = misses = 0
    # the restart run also counts persistent-cache hits/misses via the
    # jax compiler logger: its residual compile_overhead is NOT all
    # compilation — through the device tunnel it contains serialized-
    # executable loads (~0.2 s x ~160 entries) and the first sweep's
    # data-plan upload — so the restart gate is "~zero XLA cache
    # misses + serving warm < 5 s", not a wall-time bound the
    # transport can never meet
    for label, log_cache in (("cold", False), ("second_cold", True)):
        cmd = [sys.executable, "-m", "oryx_tpu.bench.coldstart", "--child",
               "--cache-dir", cache_dir,
               "--min-compile-secs", str(args.min_compile_secs),
               "--ratings", str(args.ratings), "--rank", str(args.rank)]
        if log_cache:
            cmd.append("--log-cache")
        t0 = time.perf_counter()
        out = subprocess.run(cmd, capture_output=True, text=True,
                             env=os.environ, check=False)
        wall = round(time.perf_counter() - t0, 2)
        if out.returncode != 0:
            sys.stderr.write(out.stderr)
            raise SystemExit(f"{label} child failed rc={out.returncode}")
        stats = json.loads(out.stdout.strip().splitlines()[-1])
        stats["label"] = label
        stats["process_wall_s"] = wall
        runs.append(stats)
        if log_cache:
            import re

            # count UNIQUE cache keys: the child's logging setup emits
            # every record twice (timestamped handler + plain root),
            # so a raw line count double-counts each event.  Match is
            # deliberately loose ("cache miss ... key '<key>'" in any
            # casing/wording order) so a jax release that rewords its
            # private jax._src.compiler debug lines still counts.
            text = out.stdout + out.stderr
            misses = len(set(re.findall(
                r"(?i)cache miss\b[^'\n]*'[^']*'[^'\n]*'([^']+)'", text)))
            hits = len(set(re.findall(
                r"(?i)cache hit\b[^'\n]*'[^']*'[^'\n]*'([^']+)'", text)))

    cold, warm = runs
    result = {
        "metric": "als_cold_start",
        "ratings": args.ratings, "rank": args.rank,
        # backend from the measured child process — the parent never
        # touches the device (one process on the tunnel at a time)
        "backend": warm.get("backend"),
        "min_compile_secs": args.min_compile_secs,
        # install-time warmup: the one-time cost that makes the FIRST
        # cold start below a cache-load story instead of a compile
        # story (null when --skip-warmup measured the uninstalled tax)
        "install_warmup": warmup_stats,
        "first_cold_after_install": not args.skip_warmup,
        # which jax produced/parsed the cache-log lines: a wording
        # change that flips warm_restart_ok is diagnosable from the
        # artifact alone (raw hit/miss counts ride in
        # second_cold_cache_log below)
        "jax_version": warm.get("jax_version"),
        "cache_dir": cache_dir,
        "cold": cold, "second_cold": warm,
        "compile_overhead_cold_s": cold["compile_overhead_s"],
        "compile_overhead_second_cold_s": warm["compile_overhead_s"],
        "compile_speedup": round(
            cold["compile_overhead_s"]
            / max(warm["compile_overhead_s"], 1e-9), 1),
        "second_cold_cache_log": {"xla_cache_misses": misses,
                                  "xla_cache_hits": hits},
        # hits >= 10 makes the log channel self-validating: if a jax
        # upgrade rewords/renames the private debug messages, zero hits
        # fails the gate instead of passing it vacuously.  The serving
        # bound is relative to the cold run's own serving warm-up: the
        # restart's residual is executable LOADING through the same
        # transport, so an absolute bound just measures tunnel load
        # that day (observed 3.3-11.7 s across four same-code runs).
        "warm_restart_ok": misses <= 1 and hits >= 10
        and warm["serving_warm_s"]
        < max(5.0, cold["serving_warm_s"] / 3.0),
        "warm_restart_ok_definition": (
            "~zero XLA cache misses on the logged restart (<=1 "
            "tolerates jax's per-process _broadcast_arrays helper) "
            "with >= 10 logged hits proving the detection channel "
            "works; serving warm < max(5 s, cold_serving_warm / 3).  "
            "Residual overhead is transport-bound executable/plan "
            "loading, not compilation."),
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
