"""Cold-start benchmark: process start -> trained generation + first query.

The JVM reference's layers do useful work seconds after exec (deploy/
oryx-batch/src/main/java/com/cloudera/oryx/batch/Main.java — construct,
start, await; nothing to compile).  The TPU runtime pays XLA compilation
instead — BENCH_TRAIN_r03 measured 144 s of first-epoch compile at
MovieLens-20M scale that the JVM never pays.  The persistent compilation
cache (common/compile_cache.py, `oryx.compile-cache-dir`) converts that
to a per-machine cost.  This bench quantifies it end to end:

  parent: fresh cache dir, then TWO child processes in sequence —
  child:  enable cache -> synthesize ALS data -> train 2 epochs
          (epoch1 = compile+exec, epoch2 = steady exec) -> build the
          serving model -> warm serving kernels -> first query.

Run 1 is a true cold start (empty cache); run 2 is the case that
matters operationally — a fresh process on a machine that has run
before (layer restart, redeploy, crash recovery).  The headline number
is run 2's compile overhead: epoch1-epoch2 plus serving warm.

Usage:  python -m oryx_tpu.bench.coldstart [--ratings N --rank K --out F]
One process on the device at a time; never run anything else on the
tunnel concurrently.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

__all__ = ["main"]


def _child(args) -> None:
    import numpy as np

    if args.log_cache:
        import logging

        logging.basicConfig(level=logging.WARNING)
        logging.getLogger("jax._src.compiler").setLevel(logging.DEBUG)
        logging.getLogger("jax._src.dispatch").setLevel(logging.DEBUG)

    t_proc = time.perf_counter()
    from ..common import compile_cache
    from ..common.config import from_dict

    cfg = from_dict({"oryx.compile-cache-dir": args.cache_dir})
    compile_cache.enable_from_config(cfg)

    import jax

    jax.devices()  # tunnel/backend contact
    t_backend = time.perf_counter()

    from .train import synthesize_movielens
    from ..app.als.common import ParsedRatings

    users, items, implicit_vals, _, _ = synthesize_movielens(
        n_ratings=args.ratings, seed=11)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1
    ratings = ParsedRatings(
        users=users, items=items, values=implicit_vals,
        user_ids=[f"u{i}" for i in range(n_users)],
        item_ids=[f"i{i}" for i in range(n_items)])
    t_synth = time.perf_counter()

    from ..app.als.trainer import train_als

    epoch_times: list[float] = []
    last = [time.perf_counter()]

    def on_it(i, X, Y):
        now = time.perf_counter()
        epoch_times.append(now - last[0])
        last[0] = now

    model = train_als(ratings, args.rank, lam=0.01, alpha=1.0,
                      implicit=True, iterations=2, seed=3,
                      on_iteration=on_it)
    t_train = time.perf_counter()

    from ..app.als.serving_model import ALSServingModel

    sm = ALSServingModel(features=args.rank, implicit=True)
    sm.Y.bulk_load(ratings.item_ids, model.Y)
    sm.X.bulk_load(ratings.user_ids, model.X)
    sm.warm_serving_kernels(10)
    t_warm = time.perf_counter()
    got = sm.top_n_batch(10, model.X[:2])
    assert len(got) == 2 and got[0]
    t_query = time.perf_counter()

    print(json.dumps({
        "backend_up_s": round(t_backend - t_proc, 2),
        "synth_s": round(t_synth - t_backend, 2),
        "epoch1_s": round(epoch_times[0], 2),
        "epoch2_s": round(epoch_times[1], 2),
        "train_total_s": round(t_train - t_synth, 2),
        "serving_warm_s": round(t_warm - t_train, 2),
        "first_query_s": round(t_query - t_warm, 2),
        # compile cost a restart pays beyond steady-state execution
        "compile_overhead_s": round(
            (epoch_times[0] - epoch_times[1])
            + (t_warm - t_train) + (t_query - t_warm), 2),
    }))


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--ratings", type=int, default=20_000_000)
    p.add_argument("--rank", type=int, default=100)
    p.add_argument("--cache-dir", default=None)
    p.add_argument("--child", action="store_true")
    p.add_argument("--log-cache", action="store_true")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    if args.child:
        _child(args)
        return

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="oryx-cc-")
    runs = []
    for label in ("cold", "second_cold"):
        cmd = [sys.executable, "-m", "oryx_tpu.bench.coldstart", "--child",
               "--cache-dir", cache_dir,
               "--ratings", str(args.ratings), "--rank", str(args.rank)]
        t0 = time.perf_counter()
        out = subprocess.run(cmd, capture_output=True, text=True,
                             env=os.environ, check=False)
        wall = round(time.perf_counter() - t0, 2)
        if out.returncode != 0:
            sys.stderr.write(out.stderr)
            raise SystemExit(f"{label} child failed rc={out.returncode}")
        stats = json.loads(out.stdout.strip().splitlines()[-1])
        stats["label"] = label
        stats["process_wall_s"] = wall
        runs.append(stats)

    cold, warm = runs
    result = {
        "metric": "als_cold_start",
        "ratings": args.ratings, "rank": args.rank,
        "cache_dir": cache_dir,
        "cold": cold, "second_cold": warm,
        "compile_overhead_cold_s": cold["compile_overhead_s"],
        "compile_overhead_second_cold_s": warm["compile_overhead_s"],
        "compile_speedup": round(
            cold["compile_overhead_s"]
            / max(warm["compile_overhead_s"], 1e-9), 1),
        # reference JVM pays ~0 here; parity = warm restart compile cost
        # small vs one steady epoch
        "warm_restart_ok": warm["compile_overhead_s"] < 5.0,
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
