"""Standalone HTTP traffic generator against live serving hosts.

Reference: app/oryx-app-serving/src/test/java/.../traffic/
TrafficUtil.java:63 — multi-threaded client with exponential
inter-arrival sleeps (Poisson arrivals at a requested mean QPS) firing
endpoint mixes against one or more hosts, logging latency percentiles —
and traffic/als/ALSEndpoint.java:29 (the ALS endpoint mix).

Usage (module CLI):
    python -m oryx_tpu.bench.traffic http://host:8080 \
        --qps 50 --duration 30 --workers 8 --endpoints recommend,similarity
(--endpoints filters the ALS mix to templates containing any of the
given substrings; omit it to fire the full mix.)
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading
import time
import urllib.request

import numpy as np

from ..common.rand import RandomManager
from .load import LoadStats

_log = logging.getLogger(__name__)

__all__ = ["EndpointMix", "run_traffic", "ALS_ENDPOINTS"]


class EndpointMix:
    """Weighted endpoint templates; ``{u}``/``{i}`` fill with random
    user/item ids."""

    def __init__(self, templates: dict[str, float],
                 users: int = 1000, items: int = 1000):
        total = sum(templates.values())
        self.templates = [(t, w / total) for t, w in templates.items()]
        self.users = users
        self.items = items

    def pick(self, rng) -> str:
        r = rng.random()
        acc = 0.0
        for template, weight in self.templates:
            acc += weight
            if r <= acc:
                break
        return template.replace("{u}", str(rng.integers(0, self.users))) \
                       .replace("{i}", str(rng.integers(0, self.items)))


# the reference's ALS endpoint mix (ALSEndpoint.java: recommend-heavy)
ALS_ENDPOINTS = {
    "/recommend/{u}": 0.6,
    "/similarity/{i}": 0.2,
    "/estimate/{u}/{i}": 0.1,
    "/knownItems/{u}": 0.1,
}


def run_traffic(base_urls: list[str], mix: EndpointMix,
                mean_qps: float = 10.0, duration_sec: float = 10.0,
                workers: int = 4, timeout_sec: float = 30.0) -> LoadStats:
    """Poisson-arrival load: each worker sleeps Exp(workers/qps) between
    requests (reference: TrafficUtil's exponential inter-arrival)."""
    latencies: list[float] = []
    errors = [0]
    lock = threading.Lock()
    deadline = time.perf_counter() + duration_sec
    per_worker_rate = mean_qps / max(1, workers)

    def worker(worker_id: int):
        rng = np.random.default_rng(
            RandomManager.random_seed() + worker_id)
        host = base_urls[worker_id % len(base_urls)]
        while True:
            now = time.perf_counter()
            if now >= deadline:
                return
            time.sleep(min(rng.exponential(1.0 / per_worker_rate),
                           max(0.0, deadline - now)))
            if time.perf_counter() >= deadline:
                return
            url = host + mix.pick(rng)
            start = time.perf_counter()
            try:
                with urllib.request.urlopen(url, timeout=timeout_sec) as r:
                    r.read()
                ms = (time.perf_counter() - start) * 1000.0
                with lock:
                    latencies.append(ms)
            except Exception:
                with lock:
                    errors[0] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return LoadStats(requests=len(latencies), errors=errors[0],
                     elapsed_sec=elapsed,
                     latencies_ms=np.asarray(latencies))


def main(argv: list[str] | None = None) -> int:  # pragma: no cover
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("hosts", help="comma-separated base URLs")
    parser.add_argument("--qps", type=float, default=10.0)
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--users", type=int, default=1000)
    parser.add_argument("--items", type=int, default=1000)
    parser.add_argument("--endpoints",
                        help="comma-separated substrings selecting a "
                             "subset of the ALS endpoint mix")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    templates = ALS_ENDPOINTS
    if args.endpoints:
        wanted = args.endpoints.split(",")
        templates = {t: w for t, w in ALS_ENDPOINTS.items()
                     if any(s in t for s in wanted)}
        if not templates:
            parser.error(f"no endpoints match {args.endpoints!r}")
    mix = EndpointMix(templates, users=args.users, items=args.items)
    stats = run_traffic(args.hosts.split(","), mix, args.qps,
                        args.duration, args.workers)
    print(stats.summary())
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
