"""Gateway scale-out benchmark: sustained /recommend qps through the
scatter-gather router at 1 -> 2 -> 4 catalog-shard replicas, with an
R-way replica-group dimension (``--replicas-per-shard``), a
kill-one-member availability probe, and an admission-control overload
rung.

The cluster is real processes (``python -m oryx_tpu serving --shard
i/N`` + ``router``) over a durable ``file://`` broker, so the scaling
measured is actual OS-level parallelism, not threads behind one GIL.
Every replica is pinned to ONE XLA host compute thread
(``--replica-threads``) — fixed per-replica hardware on a shared box.

On accelerator-backed (or many-core) hosts, run with real scans: each
replica's device scans its slice and sharding scales throughput
directly.  On a small shared-CPU host the co-located "device" IS the
host cores — a 1-replica baseline already saturates them, and adding
replicas re-divides the same silicon (anti-scaling that measures the
scheduler, not the gateway).  There ``--device-ms-per-mrow`` emulates
fixed-rate per-replica accelerators: every scoring dispatch sleeps
for the time a device streaming the replica's slice would take (time
∝ rows — the measured phase-A roofline shape), staged through the
``serving-scan-dispatch`` fault point, burning no host CPU.  The
artifact records the emulation constant; the regression gate compares
like cells only.

The harness publishes one synthetic model stream to the update topic
(MODEL + per-row UP messages — the exact replay path production
replicas consume), and per replica count waits for the router to
report full shard coverage, spot-checks router answers against a
direct replica merge, then walks an open-loop rate ladder
(bench/load.py's arrival-scheduled driver) to the highest sustained
rate.

With ``--replicas-per-shard R`` every shard becomes an R-way replica
group (R processes announcing the same ``(shard, of)``): the router
load-balances and hedges within each group, and the bench's
availability probe kills one member mid-load and reports the fraction
of non-partial 200s during the kill window — the measured form of "a
dead replica costs latency, not coverage".  ``--admission-max-inflight``
/ ``--admission-queue-wait-ms`` arm the router's admission control and
add an overload rung driven well past the sustained ceiling, recording
how much of the overload degraded to fast 503 + ``Retry-After`` instead
of collapse.

The router's exact result cache + single-flight coalescing
(``cluster/result_cache.py``) is armed by default: the uniform ladder
flushes the cache before every rung AND cache-busts every request with
a unique query arg (a genuinely cold miss-path cell, comparable with
pre-cache rounds — a plain uniform draw repeats users within a rung
and the accidental hits would inflate the gated number), ``--zipf a``
adds a hot-user rung whose hit rate builds across the ladder
(headline: sustained qps multiple over the cold cell + cached-hit
p50), and ``--coalesce-burst B`` fires waves of identical concurrent
requests that must collapse onto one scatter.

Since r12 the model publishes SHARDED by default (``--sharded-publish``:
manifest-carrying MODEL-REF + murmur2 slices, no per-row UP flood —
``--sharded-publish 0`` reproduces the replay publish), each cell
records per-replica ``model_load_s``/slice bytes/fallbacks, and
``--load-compare N`` publishes the same catalog both ways and boots
the same fleet against each (the O(catalog/N) load evidence).

Since r14 the router runs the C10K stack by default: ``--async`` (the
asyncio event-loop front end, ``--no-async`` reproduces the threaded
r13 configuration exactly) and ``--transport`` (the multiplexed framed
internal hop; ``--no-transport`` falls back to the HTTP/1.1 pool).
``--connections C1,C2,...`` adds a connection-count rung ladder: C
concurrent keep-alive sockets drive the cache-hit workload with
per-rung open-socket and ROUTER THREAD-COUNT telemetry — the measured
form of "the ceiling is file descriptors, not thread stacks".  Cells
with replica groups (R>1) additionally run a hedge-frame probe: a
dedicated hedge-eager router proves a hedge costs a frame, not a
connection (transport connections per replica stay 1 through the
storm).  ``--replica-cache`` arms the replica-side result cache
(cluster/result_cache.py ShardResultCache) on every replica.

``--write-heavy`` (ISSUE 17) adds the durable-ack ingest rung: a real
serving door + a real ``speed --shard 0/1`` worker over one file://
broker, an open-loop POST ``/pref`` rate ladder to the highest
sustained ACKED writes/s (the headline), a tight-gate burst proving
overload degrades to fast 503 + ``Retry-After`` (``ingest_sheds``),
and the end-to-end accounting that every 200 is durable in the input
topic and folds exactly once (``acked == durable``, zero dedup
republishes, ``ingest_to_servable_ms``).

``--ann`` (ISSUE 18) adds the IVF-ANN rung: one large-catalog
generation (``--ann-items``; the protocol cell is 10M items) published
sharded WITH the per-slice IVF index artifacts (centroids + cell
assignments — the ``oryx.als.ann.publish-index`` layout), then an
ANN-enabled serving door laddered against an exact door on the SAME
generation.  Device emulation scales the ANN door's dispatch delay by
the probed fraction (``nprobe/cells`` of the catalog streams through
phase A).  The rung reads the per-generation recall certificate off
``/metrics`` (``model_metrics.kernel_route.ann``), asserts the two
doors agree id-for-id on sampled users (certified ANN serves exact
answers), and boots a small-catalog control door proving measured-cost
routing still picks the exact kernel where ANN has no edge.

Writes ``BENCH_GATEWAY_r15.json``; ``bench/check_regression.py
--kind gateway`` gates successive rounds per (features, items,
replicas, replicas-per-shard) cell, plus ``zipf`` / ``load`` /
``mirror`` / ``conns`` / ``writes`` / ``ann`` pseudo-cells per row
when those rungs ran.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

from ..common import pmml as pmml_io
from ..common.config import keys_to_hocon
from ..kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP
from ..kafka.inproc import resolve_broker
from .load import run_recommend_open_loop

__all__ = ["run_cell", "main"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _publish_model(broker_dir: str, users: int, items: int,
                   features: int, seed: int = 5,
                   sharded: int = 0, ann_cfg=None,
                   clustered: int = 0) -> list[str]:
    """MODEL + UP replay onto the file broker — the same stream a
    batch generation publishes, so replicas load through the real
    consume path.  Writes the single-partition topic log directly in
    the broker's JSONL format (``[key, message]`` per line): the
    broker's per-record append re-reads its own write for multi-writer
    offset agreement, a tax a one-shot half-gigabyte publish need not
    pay.  A post-write ``resolve_broker`` sanity read keeps the layout
    honest.

    ``sharded`` > 0 publishes the SHARDED form instead (ISSUE 10): a
    manifest-carrying MODEL-REF whose per-murmur2-slice artifacts live
    next to the PMML, and NO per-row UP flood — each replica
    bulk-loads only its slices (O(catalog/N) load).

    ``ann_cfg`` (an ``ivf.AnnConfig``, sharded form only) additionally
    trains the generation's coarse quantizer at publish time and ships
    the IVF index artifacts (centroids + per-slice cell assignments)
    with the manifest — replicas then skip the local k-means at load
    (ISSUE 18, the ``oryx.als.ann.publish-index`` layout).

    ``clustered`` > 0 draws the item factors from a gaussian MIXTURE
    with that many components instead of one isotropic cloud.  Trained
    ALS item factors are strongly clustered (items share genres,
    price bands, popularity tiers); iid gaussian rows are the IVF
    adversarial worst case — every cell is equally likely to hold a
    query's top items, which measures the quantizer against a catalog
    no real trainer produces.  The mixture keeps the recall
    certificate honest for the structure real generations have while
    the certificate GATE still protects against the unstructured
    case (see the small/iid control doors)."""
    rng = np.random.default_rng(seed)
    os.makedirs(broker_dir, exist_ok=True)
    user_ids = [f"u{j}" for j in range(users)]
    item_ids = [f"i{j}" for j in range(items)]
    doc = pmml_io.build_skeleton_pmml()
    pmml_io.add_extension(doc, "features", features)
    pmml_io.add_extension(doc, "implicit", True)
    pmml_io.add_extension_content(doc, "XIDs", user_ids)
    pmml_io.add_extension_content(doc, "YIDs", item_ids)
    if clustered > 0:
        comp = rng.standard_normal((clustered, features))
        pick = rng.integers(0, clustered, size=items)
        y = np.round(comp[pick]
                     + 0.25 * rng.standard_normal((items, features)),
                     4).astype(np.float32)
    else:
        y = np.round(rng.standard_normal((items, features)), 4
                     ).astype(np.float32)
    x = np.round(rng.standard_normal((users, features)), 4
                 ).astype(np.float32)
    if sharded > 0:
        from ..app.als import slices as model_slices
        from ..app.als.update import save_features
        model_dir = os.path.join(broker_dir, "model-gen1")
        os.makedirs(model_dir, exist_ok=True)
        pmml_path = os.path.join(model_dir, "model.pmml.xml")
        pmml_io.write(doc, pmml_path)
        # the monolithic artifacts ride ALONGSIDE the slices, exactly
        # like the real publisher's layout — the fail-closed fallback
        # (corrupt slice, a shard count that does not divide the ring)
        # reads them, and a bench of that path must not dead-end
        save_features(os.path.join(model_dir, "Y"), item_ids, y)
        save_features(os.path.join(model_dir, "X"), user_ids, x)
        ann = None
        if ann_cfg is not None:
            from ..ops import ann as ops_ann
            from ..app.als import ivf
            centroids = ivf.train_generation_centroids(y, ann_cfg)
            ann = (centroids, ops_ann.assign_cells(y, centroids))
        slim = model_slices.publish_sliced(
            model_dir, item_ids, y, user_ids, x, None, sharded,
            ann=ann)
        envelope = model_slices.model_ref_message(pmml_path, model_dir,
                                                  slim)
        with open(os.path.join(broker_dir, "GwUp.topic.jsonl"), "a",
                  encoding="utf-8") as f:
            f.write(json.dumps([KEY_MODEL_REF, envelope]) + "\n")
        broker = resolve_broker(f"file://{broker_dir}")
        assert broker.latest_offset("GwUp") == 1
        broker.close()
        return user_ids
    with open(os.path.join(broker_dir, "GwUp.topic.jsonl"), "a",
              encoding="utf-8", buffering=1 << 20) as f:
        f.write(json.dumps([KEY_MODEL, pmml_io.to_string(doc)]) + "\n")
        for iid, row in zip(item_ids, y.tolist()):
            f.write(json.dumps(
                [KEY_UP, json.dumps(["Y", iid, row])]) + "\n")
        for uid, row in zip(user_ids, x.tolist()):
            f.write(json.dumps(
                [KEY_UP, json.dumps(["X", uid, row, []])]) + "\n")
    broker = resolve_broker(f"file://{broker_dir}")
    assert broker.latest_offset("GwUp") == 1 + items + users
    broker.close()
    return user_ids


def _write_conf(path: str, broker_dir: str, port: int,
                extra: dict) -> None:
    kv = {
        "oryx.id": "gw-bench",
        "oryx.input-topic.broker": f"file://{broker_dir}",
        "oryx.input-topic.message.topic": "GwIn",
        "oryx.input-topic.partitions": 1,
        "oryx.update-topic.broker": f"file://{broker_dir}",
        "oryx.update-topic.message.topic": "GwUp",
        "oryx.serving.model-manager-class":
            "oryx_tpu.app.als.serving_manager.ALSServingModelManager",
        "oryx.serving.application-resources": "oryx_tpu.serving.als",
        "oryx.serving.api.port": port,
        "oryx.resilience.supervisor.enabled": False,
        "oryx.cluster.heartbeat-interval-ms": 250,
        "oryx.cluster.heartbeat-ttl-ms": 1500,
    }
    kv.update(extra)
    with open(path, "w", encoding="utf-8") as f:
        f.write(keys_to_hocon(sorted(kv.items())))


def _spawn(args: list[str], conf: str, threads: int | None,
           log_path: str) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    if threads:
        # one compute thread per replica: fixed per-replica hardware
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_cpu_multi_thread_eigen=false "
                            "intra_op_parallelism_threads="
                            f"{threads}").strip()
        env["OMP_NUM_THREADS"] = str(threads)
    log = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "oryx_tpu", *args, "--conf", conf],
        env=env, stdout=log, stderr=log)


def _get_json(port: int, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read() or b"null")


def _flush_cache(port: int) -> None:
    """Drop the router's result-cache entries (404 = cache off)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/admin/cache/flush", data=b"",
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            r.read()
    except urllib.error.HTTPError as e:
        e.read()


def _cache_stats(port: int):
    try:
        return _get_json(port, "/admin/cache")
    except urllib.error.HTTPError as e:
        e.read()
        return None


def _coalesce_burst_probe(port: int, user_ids: list[str],
                          burst: int, waves: int = 10) -> dict:
    """Single-flight measurement: per wave, ``burst`` IDENTICAL
    concurrent requests against a cold key — the leader scatters once
    and the followers must latch on (verdict ``coalesced``) or, having
    arrived after completion, hit the stored entry.  The per-cell
    evidence that a thundering herd on one hot key costs ONE device
    dispatch."""
    import threading as th
    tallies: dict[str, int] = {}
    lat: list[float] = []
    errors = 0
    _flush_cache(port)
    for w in range(waves):
        uid = user_ids[w % len(user_ids)]
        url = (f"http://127.0.0.1:{port}/recommend/{uid}"
               "?howMany=10&offset=1")  # offset: distinct from ladder keys
        results: list[tuple[int, str | None, float]] = []
        lock = th.Lock()
        barrier = th.Barrier(burst)

        def one():
            barrier.wait()
            t0 = time.monotonic()
            status, verdict = 0, None
            try:
                with urllib.request.urlopen(url, timeout=60) as r:
                    r.read()
                    status = r.status
                    verdict = r.headers.get("X-Oryx-Cache")
            except Exception:  # noqa: BLE001 — counted
                pass
            with lock:
                results.append((status, verdict,
                                (time.monotonic() - t0) * 1000.0))

        threads = [th.Thread(target=one, daemon=True)
                   for _ in range(burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90.0)
        for status, verdict, ms in results:
            if status != 200:
                errors += 1
                continue
            tallies[verdict or "unstamped"] = \
                tallies.get(verdict or "unstamped", 0) + 1
            lat.append(ms)
    out = {"burst": burst, "waves": waves, "errors": errors,
           "verdicts": tallies}
    if lat:
        out["p50_ms"] = round(float(np.percentile(lat, 50)), 1)
        out["p95_ms"] = round(float(np.percentile(lat, 95)), 1)
    return out


def _proc_threads(pid: int) -> int | None:
    """The process's live thread count from /proc — the per-rung
    telemetry that proves connections stopped costing stacks."""
    try:
        with open(f"/proc/{pid}/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("Threads:"):
                    return int(line.split()[1])
    except (OSError, ValueError):
        return None
    return None


def _connection_scale_probe(port: int, pid: int, user_ids: list[str],
                            connections: int,
                            duration_sec: float = 8.0,
                            hot_users: int = 32,
                            client_threads: int = 8) -> dict:
    """The C10K rung: ``connections`` concurrent keep-alive sockets
    all driving the cache-hit workload (a small hot user set, primed
    first), served round-robin by a few client threads — the client
    deliberately has FAR fewer threads than sockets, exactly like the
    server under test.  Records 200s/errors, the cached-hit latency
    split, the router's thread count at full connection load, and the
    open-socket count."""
    import socket as sock_mod
    import threading as th
    hot = user_ids[:hot_users]
    for uid in hot:
        _get_json(port, f"/recommend/{uid}?howMany=10")
    socks = []
    for _ in range(connections):
        s = sock_mod.create_connection(("127.0.0.1", port), timeout=30)
        s.setsockopt(sock_mod.IPPROTO_TCP, sock_mod.TCP_NODELAY, 1)
        socks.append((s, s.makefile("rb")))
    ok = [0]
    errors = [0]
    hit_lat: list[float] = []
    verdicts: dict[str, int] = {}
    lock = th.Lock()
    t_end = time.monotonic() + duration_sec
    threads_mid = [None]

    def worker(my: list) -> None:
        while time.monotonic() < t_end:
            for j, (s, rf) in enumerate(my):
                if time.monotonic() >= t_end:
                    return
                uid = hot[j % len(hot)]
                t0 = time.monotonic()
                try:
                    s.sendall(
                        f"GET /recommend/{uid}?howMany=10 HTTP/1.1"
                        "\r\nHost: a\r\n\r\n".encode("latin-1"))
                    status_line = rf.readline(65537)
                    status = int(status_line.split(b" ", 2)[1])
                    clen, verdict = 0, None
                    while True:
                        h = rf.readline(65537)
                        if h in (b"\r\n", b"\n", b""):
                            break
                        if h[:15].lower() == b"content-length:":
                            clen = int(h[15:])
                        elif h[:13].lower() == b"x-oryx-cache:":
                            verdict = h[13:].strip().decode("latin-1")
                    remaining = clen
                    while remaining:
                        got = rf.read(remaining)
                        if not got:
                            raise ConnectionError("short body")
                        remaining -= len(got)
                except Exception:  # noqa: BLE001 — counted
                    with lock:
                        errors[0] += 1
                    return
                ms = (time.monotonic() - t0) * 1000.0
                with lock:
                    if status == 200:
                        ok[0] += 1
                    else:
                        errors[0] += 1
                    if verdict:
                        verdicts[verdict] = verdicts.get(verdict, 0) + 1
                        if verdict == "hit":
                            hit_lat.append(ms)

    chunk = max(1, connections // client_threads)
    workers = [th.Thread(target=worker,
                         args=(socks[i:i + chunk],), daemon=True)
               for i in range(0, connections, chunk)]
    for w in workers:
        w.start()
    time.sleep(duration_sec / 2)
    threads_mid[0] = _proc_threads(pid)
    for w in workers:
        w.join(duration_sec + 60.0)
    out = {
        "connections": connections,
        "open_sockets": len(socks),
        "ok_200": ok[0],
        "errors": errors[0],
        "achieved_qps": round(ok[0] / duration_sec, 1),
        "open_loop_sustained_qps": round(ok[0] / duration_sec, 1)
        if errors[0] == 0 else 0.0,
        "router_threads_at_load": threads_mid[0],
        "verdicts": verdicts,
    }
    if hit_lat:
        out["hit_p50_ms"] = round(float(np.percentile(hit_lat, 50)), 3)
        out["hit_p99_ms"] = round(float(np.percentile(hit_lat, 99)), 3)
    for s, rf in socks:
        try:
            s.close()
        except OSError:
            pass
    return out


def _hedge_frame_probe(work_dir: str, broker_dir: str,
                       user_ids: list[str], extra_conf: dict,
                       shards: int, requests: int = 150) -> dict:
    """Hedge-cost evidence on the framed transport: a dedicated
    hedge-EAGER router (hedge-after 1 ms, cache off) over the cell's
    live replicas — every slow-ish answer hedges, and the probe reads
    back how many hedges fired vs how many transport connections per
    replica exist.  The claim under test: hedges cost a frame, never a
    connection (sockets per replica stay 1 through the storm)."""
    port = _free_port()
    conf = os.path.join(work_dir, "hedge-probe-router.conf")
    _write_conf(conf, broker_dir, port, {
        **extra_conf,
        "oryx.cluster.transport.enabled": True,
        "oryx.cluster.hedge-after-ms": 1,
    })
    log_path = os.path.join(work_dir, "hedge-probe.log")
    proc = _spawn(["router"], conf, None, log_path)
    try:
        _await(lambda: _get_json(port, "/metrics")
               ["cluster"]["covered_shards"] == list(range(shards)),
               "hedge probe coverage")
        for i in range(requests):
            uid = user_ids[i % len(user_ids)]
            _get_json(port, f"/recommend/{uid}?howMany=10&hp={i}")
        m = _get_json(port, "/metrics")["cluster"]["scatter"]
        tp = m.get("transport") or {}
        contacted = len(tp.get("per_replica", {}))
        open_conns = tp.get("open_connections", 0)
        return {
            "requests": requests,
            "hedges": m.get("hedges"),
            "hedge_abandoned": m.get("hedge_abandoned"),
            "cancels_sent": tp.get("cancels_sent"),
            "transport_connections": open_conns,
            "replicas_contacted": contacted,
            # THE number: sockets per replica through the hedge storm
            # (1.0 = every hedge cost a frame, never a connection)
            "sockets_per_replica": round(open_conns / contacted, 2)
            if contacted else None,
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _await(predicate, what: str, timeout: float = 300.0) -> None:
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        try:
            if predicate():
                return
        except Exception:  # noqa: BLE001 — still coming up
            pass
        time.sleep(0.5)
    raise RuntimeError(f"timed out waiting for {what}")


def _get_json_retry_cold(port: int, path: str,
                         budget_sec: float = 180.0):
    """_get_json tolerating a COLD scoring path: the first dispatch a
    replica ever runs includes the XLA compile of its scan ladder,
    which can outlast the router's shard timeout — the router then
    reads the shard as down and answers 503 (or the direct call times
    out).  Those first-touch failures retry within the budget; any
    other status propagates immediately.  404 is cold too: /ready only
    means the HTTP stack is up — a replica mid-load answers 404 for a
    user its update consumer hasn't reached yet (at 1M+ items the
    replay outlasts boot by minutes)."""
    t_end = time.monotonic() + budget_sec
    while True:
        try:
            return _get_json(port, path, timeout=30.0)
        except urllib.error.HTTPError as e:
            e.read()
            if e.code not in (503, 404) or time.monotonic() >= t_end:
                raise
        except OSError:
            if time.monotonic() >= t_end:
                raise
        time.sleep(1.0)


def _probe_window(port: int, user_ids: list[str], rate_qps: float,
                  duration_sec: float, workers: int = 24) -> list[dict]:
    """Fixed-rate /recommend probe recording PER-RESPONSE verdicts —
    status, the X-Oryx-Partial marker, Retry-After, latency, and the
    completion time relative to probe start — the raw material for the
    kill-window availability fraction and the admission overload
    summary (the open-loop ladder driver only counts errors)."""
    import threading as th
    n = max(1, int(rate_qps * duration_sec))
    results: list[dict] = []
    lock = th.Lock()
    next_i = [0]
    t0 = time.monotonic()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= n:
                    return
                next_i[0] += 1
            scheduled = t0 + i / rate_qps
            now = time.monotonic()
            if scheduled > now:
                time.sleep(scheduled - now)
            sent = time.monotonic()
            uid = user_ids[i % len(user_ids)]
            url = (f"http://127.0.0.1:{port}/recommend/{uid}"
                   "?howMany=10")
            status, partial, retry_after = 0, False, None
            try:
                with urllib.request.urlopen(url, timeout=30) as r:
                    r.read()
                    status = r.status
                    partial = r.headers.get("X-Oryx-Partial") is not None
            except urllib.error.HTTPError as e:
                status = e.code
                retry_after = e.headers.get("Retry-After")
                e.read()
            except Exception:  # noqa: BLE001 — transport failure
                status = 0
            done = time.monotonic()
            with lock:
                # ms is the REQUEST's own latency (send -> response),
                # not slip against the schedule: under deliberate
                # overload the probe's own workers starve, and a shed
                # 503's cost must not inherit that local queueing
                results.append({
                    "t": done - t0,
                    "ms": (done - sent) * 1000.0,
                    "status": status, "partial": partial,
                    "retry_after": retry_after})

    threads = [th.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def _kill_window_probe(router_port: int, user_ids: list[str],
                       rate_qps: float, pre_sec: float,
                       window_sec: float, kill_fn) -> dict:
    """Drive steady load, kill one replica-group member at ``pre_sec``,
    and report availability — the fraction of non-partial 200s — over
    the kill window (kill instant to probe end, TTL expiry included:
    hedged failover must hide the death even BEFORE age-out)."""
    import threading as th
    timer = th.Timer(pre_sec, kill_fn)
    timer.start()
    try:
        results = _probe_window(router_port, user_ids, rate_qps,
                                pre_sec + window_sec)
    finally:
        timer.cancel()  # no-op once fired
    window = [r for r in results if r["t"] >= pre_sec]
    ok = [r for r in window
          if r["status"] == 200 and not r["partial"]]
    return {
        "rate_qps": rate_qps,
        "window_requests": len(window),
        "ok_full": len(ok),
        "partials": sum(1 for r in window if r["partial"]),
        "errors": sum(1 for r in window
                      if r["status"] != 200),
        "availability": round(len(ok) / len(window), 4)
        if window else None,
    }


def _overload_probe(router_port: int, user_ids: list[str],
                    rate_qps: float, duration_sec: float) -> dict:
    """Drive the router well past its sustained ceiling with admission
    control armed: overload must degrade to FAST 503 + Retry-After,
    not the queueing collapse of the un-gated front end."""
    # worker pool must exceed the admission cap, or the probe itself
    # bounds inflight below the gate and nothing ever sheds
    results = _probe_window(router_port, user_ids, rate_qps,
                            duration_sec,
                            workers=min(256, max(128,
                                                 int(rate_qps * 1.5))))
    ok = [r for r in results if r["status"] == 200]
    shed = [r for r in results if r["status"] == 503]

    def _p50(rows):
        return round(float(np.percentile(
            [r["ms"] for r in rows], 50)), 1) if rows else None

    return {
        "offered_qps": rate_qps,
        "requests": len(results),
        "ok_200": len(ok),
        "shed_503": len(shed),
        "shed_fraction": round(len(shed) / len(results), 4)
        if results else None,
        "shed_with_retry_after": sum(
            1 for r in shed if r["retry_after"]),
        "other_errors": len(results) - len(ok) - len(shed),
        "p50_ok_ms": _p50(ok),
        # the whole point: a shed answer costs ~a round trip, not a
        # queue residence
        "p50_shed_ms": _p50(shed),
    }


def run_cell(replicas: int, items: int, features: int, users: int,
             rates: list[float], duration_sec: float,
             replica_threads: int, work_dir: str,
             broker_dir: str | None = None,
             user_ids: list[str] | None = None,
             device_ms_per_mrow: float = 0.0,
             spot_users: int = 20,
             tracing_sample: float | None = None,
             replicas_per_shard: int = 1,
             kill_member_probe: bool = False,
             admission: dict | None = None,
             overload_factor: float = 3.0,
             cache: bool = True,
             zipf: float = 0.0,
             coalesce_burst: int = 0,
             sharded_publish: int = 0,
             async_mode: bool = False,
             transport: bool = False,
             replica_cache: bool = False,
             connections: "list[int] | None" = None) -> dict:
    publish_s = 0.0
    if broker_dir is None:
        broker_dir = os.path.join(work_dir, f"broker-{replicas}")
        os.makedirs(broker_dir, exist_ok=True)
        t0 = time.time()
        user_ids = _publish_model(broker_dir, users, items, features,
                                  sharded=sharded_publish)
        publish_s = time.time() - t0

    procs: list[subprocess.Popen] = []
    # member grid: replicas shards x replicas_per_shard group members
    members = [(s, r) for s in range(replicas)
               for r in range(replicas_per_shard)]
    member_ports = {m: _free_port() for m in members}
    member_procs: dict[tuple[int, int], subprocess.Popen] = {}
    replica_ports = list(member_ports.values())
    router_port = _free_port()
    log_path = os.path.join(
        work_dir, f"cell-{replicas}x{replicas_per_shard}.log")
    # per-replica catalog slice: what the emulated device streams
    slice_rows = items / replicas
    try:
        # tracing enabled on every process when requested: the
        # overhead cell runs with a sample ratio low enough that the
        # measured delta is the UNsampled per-request branch cost
        obs_extra = {}
        if tracing_sample is not None:
            obs_extra = {
                "oryx.obs.tracing.enabled": True,
                "oryx.obs.tracing.sample-ratio": tracing_sample,
            }
        for s, r in members:
            conf = os.path.join(
                work_dir,
                f"replica-{replicas}x{replicas_per_shard}-{s}-{r}.conf")
            extra = {
                "oryx.cluster.enabled": True,
                "oryx.cluster.shard": f"{s}/{replicas}",
                "oryx.cluster.replica-id":
                    f"s{s}r{r}of{replicas}",
                **obs_extra,
            }
            if transport:
                # the framed internal hop: frame listener next to the
                # HTTP door, port advertised via the heartbeat
                extra["oryx.cluster.transport.enabled"] = True
            if replica_cache:
                extra["oryx.cluster.replica-cache.enabled"] = True
            if device_ms_per_mrow > 0:
                # fixed-rate accelerator emulation: each scoring
                # dispatch sleeps for the time a device streaming this
                # replica's slice would take (time ∝ rows — the
                # measured phase-A roofline shape), WITHOUT burning
                # host CPU.  On a shared CPU box this is the only
                # honest way to measure the GATEWAY's scaling: a real
                # deployment gives each replica its own accelerator,
                # while a co-located CPU "device" just splits the same
                # cores.  Staged through the standard fault registry.
                # max-batch gives the emulated device a finite
                # per-window capacity (a real device's window ladder
                # is bounded too); without it, unbounded coalescing
                # amortizes ANY fixed window cost away and the
                # measurement collapses back into host-CPU scheduling.
                # pipeline-depth 2 pins the batcher's in-flight cap
                # (one window executing + one queued — a double-
                # buffered device stream): the adaptive cap learns
                # from completion gaps that a sleep-emulated device
                # renders meaningless, and wherever it wanders the
                # cell's ceiling follows — two same-config runs
                # measured 1.8x apart.  Pinned, the emulated ceiling
                # is deterministic: pipeline x max-batch / delay.
                delay = device_ms_per_mrow * slice_rows / 1e6
                extra.update({
                    "oryx.serving.api.max-batch": 8,
                    "oryx.serving.api.scoring-pipeline-depth": 2,
                    "oryx.resilience.faults.serving-scan-dispatch"
                    ".mode": "delay",
                    "oryx.resilience.faults.serving-scan-dispatch"
                    ".times": -1,
                    "oryx.resilience.faults.serving-scan-dispatch"
                    ".delay-ms": round(delay, 3),
                })
            _write_conf(conf, broker_dir, member_ports[(s, r)], extra)
            proc = _spawn(["serving", "--shard", f"{s}/{replicas}"],
                          conf, replica_threads, log_path)
            procs.append(proc)
            member_procs[(s, r)] = proc
        conf = os.path.join(
            work_dir, f"router-{replicas}x{replicas_per_shard}.conf")
        router_extra = dict(obs_extra)
        if async_mode:
            # the C10K event-loop front end (--no-async reproduces the
            # threaded r13 router exactly)
            router_extra["oryx.cluster.async.enabled"] = True
        if transport:
            router_extra["oryx.cluster.transport.enabled"] = True
        if device_ms_per_mrow > 0:
            # hedge only on a genuine stall: the default 100 ms window
            # sits far BELOW an emulated cell's per-dispatch delay, so
            # with R-way groups nearly every request would hedge to a
            # sibling and the duplicated work erases the group's extra
            # capacity.  5x the dispatch delay sits past the queueing
            # tail a sustained rung produces (p50 ~2 windows) — the
            # production guidance of hedge-after ~ p95+.
            delay = device_ms_per_mrow * slice_rows / 1e6
            router_extra["oryx.cluster.hedge-after-ms"] = \
                max(1000, int(5 * delay))
        if admission:
            router_extra.update(admission)
        if cache:
            # the exact result cache + single-flight coalescing
            # (cluster/result_cache.py): armed for every rung — the
            # uniform ladder flushes before each rung so it stays a
            # miss-path (overhead) measurement, the Zipf rung lets the
            # hot-user hit rate build, the burst rung measures the
            # latch
            router_extra.update({
                "oryx.cluster.cache.enabled": True,
                "oryx.cluster.coalesce.enabled": True,
            })
        _write_conf(conf, broker_dir, router_port, router_extra)
        procs.append(_spawn(["router"], conf, None, log_path))

        def _loaded(port: int) -> bool:
            m = _get_json(port, "/shard/meta")
            # ready fires at the 80% load gate, with the user store
            # still filling (items stream first); the bench drives
            # real user ids, so wait for the full replay
            return bool(m.get("ready")) and m.get("users", 0) >= users

        t0 = time.time()
        _await(lambda: all(_loaded(p) for p in replica_ports),
               "replica model load", timeout=900.0)
        load_s = time.time() - t0
        # per-replica load telemetry (sharded model distribution,
        # ISSUE 10): each replica's own receipt-to-servable clock,
        # slice bytes read, and fallbacks — the evidence that a
        # slice-loaded fleet loads O(catalog/N) instead of replaying
        # the whole stream
        per_replica_load = []
        for p in replica_ports:
            g = _get_json(p, "/metrics").get("freshness", {})
            per_replica_load.append({
                "port": p,
                "model_load_s": g.get("model_load_s"),
                "model_slice_bytes": g.get("model_slice_bytes"),
                "slice_load_fallbacks": g.get("slice_load_fallbacks"),
            })
        loads = [r["model_load_s"] for r in per_replica_load
                 if r["model_load_s"]]
        model_load = {
            "mode": "slices" if sharded_publish > 0 else "replay",
            "slices": sharded_publish or None,
            "bench_wall_s": round(load_s, 1),
            "per_replica": per_replica_load,
            "max_replica_load_s": round(max(loads), 3) if loads else None,
            "fallbacks": sum(r["slice_load_fallbacks"] or 0
                             for r in per_replica_load),
        }
        _await(lambda: _get_json(router_port, "/metrics")
               ["cluster"]["covered_shards"] == list(range(replicas)),
               "router coverage")

        # correctness spot-check: router merge == exact merge of the
        # replicas' own /shard/recommend answers (one member per
        # shard — group siblings hold identical slices and would
        # double-count every row)
        spot_ports = [member_ports[(s, 0)] for s in range(replicas)]
        # first-touch scoring compiles per process: warm every member
        # directly (so the router's first scatter never sees a shard
        # stuck in its XLA compile and degrades to partial/503), then
        # one request through the router itself
        for p in member_ports.values():
            _get_json_retry_cold(
                p, f"/shard/recommend/{user_ids[0]}?howMany=10")
        _get_json_retry_cold(router_port,
                             f"/recommend/{user_ids[0]}?howMany=10")
        spot_ok = True
        for uid in user_ids[:spot_users]:
            got = [d["id"] for d in _get_json_retry_cold(
                router_port, f"/recommend/{uid}?howMany=10")]
            rows = []
            for p in spot_ports:
                payload = _get_json(p, f"/shard/recommend/{uid}"
                                       "?howMany=10")
                rows.extend(tuple(r) for r in payload["rows"])
            rows.sort(key=lambda r: (-r[1], r[2], r[0]))
            want = [r[0] for r in rows[:10]]
            if got != want:
                spot_ok = False
                break

        # warm-up burst: compiles the serving window ladder (and the
        # router's connection pools) before any rung is judged — a
        # multi-second XLA compile inside a rated rung reads as
        # saturation
        run_recommend_open_loop(
            f"http://127.0.0.1:{router_port}", user_ids, rate_qps=30,
            duration_sec=max(6.0, duration_sec), workers=64)

        def _run_ladder(flush_each_rung: bool, zipf_a=None,
                        cache_bust=False):
            """Walk the rate ladder to the highest sustained rung; one
            retry per rung absorbs a transient stall (a late compile,
            a heartbeat-file fsync burst) before the rung counts."""
            ladder, best = [], None
            for rate in rates:
                out = None
                for _attempt in range(2):
                    if flush_each_rung:
                        _flush_cache(router_port)
                    out = run_recommend_open_loop(
                        f"http://127.0.0.1:{router_port}", user_ids,
                        rate_qps=rate, duration_sec=duration_sec,
                        workers=min(256, max(64, int(rate))),
                        zipf_a=zipf_a, cache_bust=cache_bust)
                    if out["sustained"]:
                        break
                ladder.append(out)
                if out["sustained"]:
                    best = out
                else:
                    break
            return ladder, best

        # uniform COLD (miss-path) cell, comparable with pre-cache
        # rounds: every rung starts from an empty cache AND every
        # request carries a unique cache-busting arg — without it a
        # uniform draw repeats users within a rung (birthday effect)
        # and the accidental hits would inflate the gated cold number,
        # masking scatter-path regressions behind the cache
        ladder, best = _run_ladder(flush_each_rung=cache,
                                   cache_bust=cache)

        # hot-user Zipf rung (the result cache's design load): same
        # rate ladder, skewed user draw, NO flushes between rungs —
        # the hit rate builds exactly as production's would.  Headline
        # = sustained qps vs the cold cell + the cached-hit p50.
        zipf_report = None
        if cache and zipf > 0:
            _flush_cache(router_port)
            z_ladder, z_best = _run_ladder(flush_each_rung=False,
                                           zipf_a=zipf)
            zipf_report = {
                "a": zipf,
                "open_loop_sustained_qps":
                    z_best["achieved_qps"] if z_best else 0.0,
                "sustained_p50_ms": z_best["p50_ms"] if z_best else None,
                "cache": z_best.get("cache") if z_best else None,
                "admin_cache": _cache_stats(router_port),
                "ladder": z_ladder,
            }

        # single-flight burst rung: a thundering herd on one cold hot
        # key must collapse to one scatter
        burst_report = None
        if cache and coalesce_burst > 1:
            burst_report = _coalesce_burst_probe(
                router_port, user_ids, coalesce_burst)

        # connection-count rung ladder (C10K acceptance): C concurrent
        # keep-alive sockets on the cache-hit workload, with open-
        # socket and router-thread telemetry per rung — only
        # meaningful with the cache armed (hits are the workload)
        conns_report = None
        if cache and connections:
            router_pid = procs[-1].pid
            rungs = []
            for cnum in connections:
                rung = _connection_scale_probe(
                    router_port, router_pid, user_ids, cnum,
                    duration_sec=max(6.0, duration_sec))
                rungs.append(rung)
                print(json.dumps(rung), file=sys.stderr)
            top = rungs[-1]
            conns_report = {**top, "rungs": rungs,
                            "router_threads_idle":
                                _proc_threads(router_pid)}

        # hedge-cost probe (framed transport, replica groups only): a
        # dedicated hedge-eager router proves hedges cost a frame, not
        # a connection
        hedge_frames = None
        if transport and replicas_per_shard > 1:
            hedge_frames = _hedge_frame_probe(
                work_dir, broker_dir, user_ids, dict(obs_extra),
                replicas)
            print(json.dumps(hedge_frames), file=sys.stderr)
        if best and best.get("worst_sampled"):
            # worst sampled requests of the best rung: each trace id
            # names a recorded span tree on the router's /admin/traces
            print("worst-p99 sampled requests: " + ", ".join(
                f"{w['ms']}ms trace={w['trace']}"
                for w in best["worst_sampled"]), file=sys.stderr)
        m = _get_json(router_port, "/metrics")
        partials = m["counters"].get("partial_answers", 0)
        admission_stats = m["cluster"].get("admission")
        scatter_stats = m["cluster"].get("scatter")

        # overload rung FIRST (the cluster is still intact — a
        # post-kill group would bias shed fraction and latency): drive
        # well past the sustained ceiling with admission armed — the
        # shed fraction and its p50 are the measured "fast 503" story
        admission_overload = None
        if admission:
            base = best["achieved_qps"] if best else 50.0
            admission_overload = _overload_probe(
                router_port, user_ids, base * overload_factor,
                max(8.0, duration_sec))
            # let the admitted backlog (bounded by the inflight cap)
            # drain before the availability probe is judged
            time.sleep(6.0)

        # availability probe: kill one group member under steady load;
        # a 2-of-2 group must keep answering FULL (non-partial) 200s —
        # hedged failover before age-out, sibling-only routing after
        kill_probe = None
        if kill_member_probe and replicas_per_shard > 1:
            probe_rate = max(
                20.0, (best["achieved_qps"] if best else 40.0) * 0.5)
            victim = member_procs[(0, replicas_per_shard - 1)]
            kill_probe = _kill_window_probe(
                router_port, user_ids, probe_rate, pre_sec=3.0,
                window_sec=max(8.0, duration_sec),
                kill_fn=victim.kill)

        return {
            "replicas": replicas,
            "replicas_per_shard": replicas_per_shard,
            "items": items,
            "features": features,
            "users": users,
            "replica_threads": replica_threads,
            "tracing_sample": tracing_sample,
            "emulated_device_ms_per_mrow": device_ms_per_mrow,
            "emulated_dispatch_delay_ms":
                round(device_ms_per_mrow * slice_rows / 1e6, 3),
            "emulated_window_cap": (8 if device_ms_per_mrow > 0
                                    else None),
            "emulated_pipeline_depth": (2 if device_ms_per_mrow > 0
                                        else None),
            "publish_s": round(publish_s, 1),
            "model_load_s": round(load_s, 1),
            "model_load": model_load,
            "merge_spotcheck_ok": spot_ok,
            "partial_answers_during_run": partials,
            "open_loop_sustained_qps":
                best["achieved_qps"] if best else 0.0,
            "sustained_p50_ms": best["p50_ms"] if best else None,
            "sustained_p95_ms": best["p95_ms"] if best else None,
            "cache_armed": cache,
            "async_front_end": async_mode,
            "framed_transport": transport,
            "replica_cache_armed": replica_cache,
            "zipf": zipf_report,
            "coalesce_burst": burst_report,
            "conns": conns_report,
            "hedge_frames": hedge_frames,
            "cache_stats_after_run": _cache_stats(router_port),
            "kill_probe": kill_probe,
            "admission": admission or None,
            "admission_stats_after_ladder": admission_stats,
            "scatter_stats_after_ladder": scatter_stats,
            "admission_overload": admission_overload,
            "ladder": ladder,
        }
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _measure_fleet_load(work_dir: str, broker_dir: str, shards: int,
                        replica_threads: int, tag: str) -> dict:
    """Boot a ``shards``-way fleet against an already-published broker
    and measure spawn-to-all-ready wall clock plus each replica's own
    receipt-to-servable ``model_load_s`` gauge — the load-compare
    probe's one measurement."""
    procs, ports = [], []
    log_path = os.path.join(work_dir, f"load-{tag}.log")
    try:
        for s in range(shards):
            port = _free_port()
            conf = os.path.join(work_dir, f"load-{tag}-{s}.conf")
            _write_conf(conf, broker_dir, port, {
                "oryx.cluster.enabled": True,
                "oryx.cluster.shard": f"{s}/{shards}",
                "oryx.cluster.replica-id": f"load{tag}{s}",
            })
            procs.append(_spawn(["serving", "--shard", f"{s}/{shards}"],
                                conf, replica_threads, log_path))
            ports.append(port)
        t0 = time.time()
        _await(lambda: all(
            _get_json(p, "/shard/meta").get("ready")
            and _get_json(p, "/metrics").get(
                "model_fraction_loaded", 0) >= 1.0
            and _get_json(p, "/metrics").get(
                "freshness", {}).get("model_load_s", 0) > 0
            for p in ports), f"load probe {tag}", timeout=900.0)
        wall = time.time() - t0
        out = {"wall_s": round(wall, 1), "per_replica": []}
        for p in ports:
            g = _get_json(p, "/metrics").get("freshness", {})
            out["per_replica"].append({
                "model_load_s": g.get("model_load_s"),
                "model_slice_bytes": g.get("model_slice_bytes"),
                "slice_load_fallbacks": g.get("slice_load_fallbacks"),
            })
        loads = [r["model_load_s"] for r in out["per_replica"]
                 if r["model_load_s"]]
        out["max_replica_load_s"] = round(max(loads), 3) if loads else None
        return out
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def run_load_compare(work_dir: str, items: int, features: int,
                     users: int, shards: int, replica_threads: int,
                     sharded: int) -> dict:
    """The O(catalog/N) load measurement (ISSUE 10 acceptance): the
    SAME catalog published both ways — full-stream replay vs sharded
    manifest — loaded by the same ``shards``-way fleet.  Reports both
    spawn-to-ready wall clocks and the replicas' own
    receipt-to-servable clocks, plus their ratio (target: sliced ≤ 60%
    of replay at 2 shards)."""
    replay_dir = os.path.join(work_dir, "load-replay-broker")
    sliced_dir = os.path.join(work_dir, "load-sliced-broker")
    _publish_model(replay_dir, users, items, features)
    _publish_model(sliced_dir, users, items, features, sharded=sharded)
    replay = _measure_fleet_load(work_dir, replay_dir, shards,
                                 replica_threads, "replay")
    sliced = _measure_fleet_load(work_dir, sliced_dir, shards,
                                 replica_threads, "sliced")
    out = {"items": items, "features": features, "shards": shards,
           "slices": sharded, "replay": replay, "sliced": sliced}
    if replay["max_replica_load_s"] and sliced["max_replica_load_s"]:
        out["replica_load_ratio"] = round(
            sliced["max_replica_load_s"] / replay["max_replica_load_s"],
            3)
    if replay["wall_s"]:
        out["wall_ratio"] = round(sliced["wall_s"] / replay["wall_s"], 3)
    return out


def run_mirror_probe(work_dir: str, records: int = 2000,
                     features: int = 8,
                     poll_interval_ms: int = 100) -> dict:
    """The two-region cell (``--regions 2``, ISSUE 11): one real
    ``python -m oryx_tpu mirror`` process replaying region A's update
    topic into region B's over durable file:// brokers, measuring

    - **steady-state** ``cross_region_staleness_ms`` while the link is
      healthy and drained (the mirror's own gauge, sampled);
    - **healed-partition catch-up**: the link goes down (mirror
      killed), ``records`` ts-stamped UP records accumulate on the
      source, the link heals (fresh mirror, same durable checkpoint —
      the crash-resume path), and the probe clocks source-head to
      drained.  Catch-up speed (records/s) is the gated headline: a
      region must not fall further behind while it is catching up.
    """
    a_dir = os.path.join(work_dir, "mirror-region-a")
    b_dir = os.path.join(work_dir, "mirror-region-b")
    ckpt = os.path.join(work_dir, "mirror-ckpt")
    os.makedirs(a_dir, exist_ok=True)
    os.makedirs(b_dir, exist_ok=True)

    def _append_ups(n: int, start: int) -> None:
        now_ms = int(time.time() * 1000)
        vec = [round(0.01 * j, 4) for j in range(features)]
        with open(os.path.join(a_dir, "GwUp.topic.jsonl"), "a",
                  encoding="utf-8") as f:
            for j in range(start, start + n):
                f.write(json.dumps(
                    ["UP", json.dumps(["X", f"mu{j}", vec, []]),
                     {"ts": str(now_ms)}]) + "\n")

    obs_port = _free_port()
    conf = os.path.join(work_dir, "mirror.conf")
    _write_conf(conf, b_dir, _free_port(), {
        "oryx.cluster.region.name": "bench-b",
        "oryx.cluster.region.mirror.source-broker": f"file://{a_dir}",
        "oryx.cluster.region.mirror.source-region": "bench-a",
        "oryx.cluster.region.mirror.checkpoint-dir": ckpt,
        "oryx.cluster.region.mirror.poll-interval-ms": poll_interval_ms,
        "oryx.obs.metrics-port": obs_port,
        "oryx.resilience.supervisor.enabled": False,
    })
    log_path = os.path.join(work_dir, "mirror-probe.log")

    def _gauges() -> dict:
        return _get_json(obs_port, "/metrics").get("freshness", {})

    _append_ups(records // 4, 0)  # a warm link carries live traffic
    proc = _spawn(["mirror"], conf, None, log_path)
    try:
        _await(lambda: _gauges().get("mirror_lag_records") == 0,
               "mirror steady drain", timeout=240.0)
        time.sleep(3 * poll_interval_ms / 1000.0)
        steady = [_gauges().get("cross_region_staleness_ms")
                  for _ in range(5)]
        steady = [s for s in steady if s is not None]
    finally:
        proc.kill()  # the partition: the link is gone, not drained
        proc.wait(timeout=15)
    _append_ups(records, records // 4)  # backlog behind the partition
    t0 = time.time()
    proc = _spawn(["mirror"], conf, None, log_path)
    try:
        _await(lambda: _gauges().get("mirror_lag_records") == 0,
               "mirror catch-up", timeout=600.0)
        catch_up_s = time.time() - t0
        counters = _get_json(obs_port, "/metrics")["counters"]
    finally:
        proc.kill()
        proc.wait(timeout=15)
    return {
        "records": records,
        "steady_staleness_ms": (round(float(np.median(steady)), 1)
                                if steady else None),
        "catch_up_s": round(catch_up_s, 2),
        # includes the fresh process's spawn cost — honest: that IS
        # the heal-to-drained wall clock a failover runbook sees
        "catch_up_records_per_s": round(records / catch_up_s, 1),
        "replayed": counters.get("mirror_records_replayed"),
        "dedup_skips": counters.get("mirror_dedup_skips", 0),
    }


def _write_window(port: int, n_users: int, n_items: int,
                  rate_qps: float, duration_sec: float,
                  workers: int = 48) -> list[dict]:
    """Fixed-rate POST ``/pref/{u}/{i}`` driver recording per-response
    verdicts — status, Retry-After, latency — the write-path twin of
    ``_probe_window``.  Every 200 is a durable-ack claim the probe's
    broker-side accounting checks afterwards."""
    import threading as th
    n = max(1, int(rate_qps * duration_sec))
    results: list[dict] = []
    lock = th.Lock()
    next_i = [0]
    t0 = time.monotonic()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= n:
                    return
                next_i[0] += 1
            scheduled = t0 + i / rate_qps
            now = time.monotonic()
            if scheduled > now:
                time.sleep(scheduled - now)
            sent = time.monotonic()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/pref/u{i % n_users}"
                f"/i{i % n_items}", data=b"1.0", method="POST")
            status, retry_after = 0, None
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    r.read()
                    status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
                retry_after = e.headers.get("Retry-After")
                e.read()
            except Exception:  # noqa: BLE001 — transport failure
                status = 0
            done = time.monotonic()
            with lock:
                results.append({
                    "t": done - t0,
                    "ms": (done - sent) * 1000.0,
                    "status": status, "retry_after": retry_after})

    threads = [th.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def run_write_heavy_probe(work_dir: str, users: int = 200,
                          items: int = 120, features: int = 4,
                          rates: list[float] | None = None,
                          duration: float = 2.5) -> dict:
    """The ``--write-heavy`` rung (ISSUE 17): the durable-ack write
    path measured end to end over real processes — one serving door
    (``python -m oryx_tpu serving``, ingest gate armed) and one
    ``speed --shard 0/1`` worker sharing a durable file:// broker.

    Three measurements, one broker-side ledger:

    - **sustained acked writes/s** (the gated headline): an open-loop
      POST ``/pref`` rate ladder; a rung sustains only with ZERO
      non-shed errors and zero sheds — a 200 is a durable-ack claim,
      so the headline counts nothing weaker;
    - **overload shape**: a second door with ``max-inflight-sends: 1``
      takes a concurrent burst — overload must degrade to fast 503 +
      ``Retry-After`` (``ingest_sheds``), never slow errors;
    - **the ledger**: acked 200s across ALL windows must equal the
      input-topic offset delta (nothing acked-but-lost, nothing
      silently half-written), and after the speed worker drains, its
      checkpoint fence + dedup counters prove every acked record
      folded exactly once, with ``ingest_to_servable_ms`` as the
      freshness evidence.
    """
    wr_dir = os.path.join(work_dir, "write-broker")
    ckpt = os.path.join(work_dir, "write-speed-ckpt")
    _publish_model(wr_dir, users, items, features)
    api_port, tight_port = _free_port(), _free_port()
    speed_obs = _free_port()
    speed_conf = os.path.join(work_dir, "write-speed.conf")
    _write_conf(speed_conf, wr_dir, _free_port(), {
        "oryx.speed.model-manager-class":
            "oryx_tpu.app.als.speed.ALSSpeedModelManager",
        "oryx.speed.checkpoint-dir": ckpt,
        "oryx.speed.streaming.generation-interval-sec": 1,
        "oryx.obs.metrics-port": speed_obs,
    })
    serve_conf = os.path.join(work_dir, "write-serving.conf")
    _write_conf(serve_conf, wr_dir, api_port, {
        # bounded but generous: the ladder must measure the broker,
        # not the gate — the tight door below measures the gate
        "oryx.serving.ingest.max-inflight-sends": 64,
        "oryx.serving.ingest.retry-after-sec": 1,
    })
    tight_conf = os.path.join(work_dir, "write-tight.conf")
    _write_conf(tight_conf, wr_dir, tight_port, {
        "oryx.serving.ingest.max-inflight-sends": 1,
        "oryx.serving.ingest.retry-after-sec": 1,
    })
    log_path = os.path.join(work_dir, "write-probe.log")
    broker = resolve_broker(f"file://{wr_dir}")
    n0 = broker.latest_offset("GwIn")

    def _speed_gauges() -> dict:
        return _get_json(speed_obs, "/metrics").get("freshness", {})

    procs = [_spawn(["speed", "--shard", "0/1"], speed_conf, None,
                    log_path)]
    try:
        # the worker's fold fence starts at the CURRENT input head
        # (tail semantics), so it must be up before the first write —
        # and its model replayed, or early folds would be skipped
        _await(lambda: (_speed_gauges().get("update_lag_records") == 0
                        and _speed_gauges()
                        .get("model_generation_age_sec") is not None),
               "write probe speed worker model replay", timeout=300.0)
        procs.append(_spawn(["serving"], serve_conf, None, log_path))
        procs.append(_spawn(["serving"], tight_conf, None, log_path))
        for port in (api_port, tight_port):
            _await(lambda p=port: _get_json(p, "/ready") is None,
                   "write probe serving door", timeout=300.0)

        ladder, acked, sustained = [], 0, 0.0
        for rate in rates or [150.0, 300.0, 600.0, 1200.0, 2400.0]:
            results = _write_window(api_port, users, items, rate,
                                    duration)
            # a None-returning mutation renders as 204 (lambda_rt/
            # http.py): that IS the durable ack
            ok = [r for r in results if r["status"] in (200, 204)]
            shed = [r for r in results if r["status"] == 503]
            span = max(r["t"] for r in results)
            achieved = round(len(ok) / span, 1) if span else 0.0
            rung_ok = (len(ok) == len(results)
                       and achieved >= 0.9 * rate)
            ladder.append({
                "offered_qps": rate, "requests": len(results),
                "acked": len(ok), "shed_503": len(shed),
                "other_errors": len(results) - len(ok) - len(shed),
                "achieved_acked_qps": achieved,
                "p50_ack_ms": round(float(np.percentile(
                    [r["ms"] for r in ok], 50)), 1) if ok else None,
                "sustained": rung_ok,
            })
            acked += len(ok)
            if rung_ok:
                sustained = achieved
            else:
                break

        # the overload burst, against the tight door: concurrency >>
        # the gate's one slot, so admission MUST shed — the shape of
        # the shed (fast, Retry-After-stamped) is what's under test
        over = _write_window(tight_port, users, items,
                             max(2000.0, 2.0 * sustained), 1.5,
                             workers=64)
        over_ok = [r for r in over if r["status"] in (200, 204)]
        over_shed = [r for r in over if r["status"] == 503]
        acked += len(over_ok)
        overload = {
            "requests": len(over),
            "acked": len(over_ok),
            "shed_503": len(over_shed),
            "shed_with_retry_after": sum(
                1 for r in over_shed if r["retry_after"]),
            "other_errors": len(over) - len(over_ok) - len(over_shed),
            "p50_shed_ms": round(float(np.percentile(
                [r["ms"] for r in over_shed], 50)), 1)
            if over_shed else None,
        }

        # the ledger: every ack durable, every durable record folded
        # exactly once — read AFTER the worker drains to the head
        _await(lambda: _speed_gauges().get("input_lag_records") == 0,
               "write probe fold-in drain", timeout=300.0)
        durable = broker.latest_offset("GwIn") - n0
        m = _get_json(speed_obs, "/metrics")
        gauges = m.get("freshness", {})
        counters = m.get("counters", {})
        serving_counters = _get_json(tight_port, "/metrics").get(
            "counters", {})
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=15)
    return {
        "open_loop_sustained_qps": sustained,
        "ladder": ladder,
        "acked": acked,
        "durable": durable,
        "acked_equals_durable": acked == durable,
        "overload": overload,
        "ingest_sheds": serving_counters.get("ingest_sheds", 0),
        "ingest_to_servable_ms": gauges.get("ingest_to_servable_ms"),
        "speed_checkpoint_age_sec":
            gauges.get("speed_checkpoint_age_sec"),
        "dedup_skips": counters.get("speed_shard_dedup_skips", 0),
    }


def run_ann_probe(work_dir: str, items: int, features: int,
                  users: int, duration_sec: float,
                  device_ms_per_mrow: float = 0.0,
                  cells: int = 1024, nprobe: int = 32,
                  sharded: int = 24,
                  small: "tuple[str, int, list[str]] | None" = None
                  ) -> dict:
    """The ``--ann`` rung (ISSUE 18): the IVF-ANN phase-A path measured
    door-to-door against the exact kernel on the SAME synthetic
    generation — one sharded publish carrying the per-slice index
    artifacts (centroids + cell assignments, the
    ``oryx.als.ann.publish-index`` layout), two real serving doors over
    it: one with ``oryx.als.ann.enabled`` and one without.

    The generation's item factors are a gaussian MIXTURE (cells/4
    components — see ``_publish_model(clustered=...)``): iid
    gaussian rows are the IVF adversarial worst case no trained ALS
    catalog resembles, and this rung measures the serving path, not
    the quantizer's behavior on structureless data (the certificate
    gate covers that — an unstructured generation simply refuses to
    route, as the smoke-tested iid case shows).

    Under ``--device-ms-per-mrow`` emulation the ANN door's dispatch
    delay scales by the PROBED fraction (``nprobe / cells`` of the
    catalog's rows stream through phase A instead of all of them — the
    measured phase-A roofline shape applied to the rows the IVF kernel
    actually touches); the exact door pays the full-catalog delay.
    Both constants are recorded.  Because that delay is fixed at door
    boot, the gated headline is WITHHELD (None — check_regression
    skips an absent cell) unless the door's measured route actually
    chose the ``ivf`` kind: an ANN door serving the exact kernel
    under the probed-fraction delay would report a fantasy.

    ANN answers may differ from the exact door's within the recall
    budget — pruned cells are what the load-time certificate
    MEASURES, not what the per-window bound covers — so the probe
    records the sampled users' top-10 overlap rather than asserting
    byte-equality.

    Reports each door's sustained qps + p50/p99, the ANN door's recall
    certificate as published on ``/metrics``
    (``model_metrics.kernel_route.ann``), index bytes/fallbacks, and
    the speedup ratio.  ``small`` = (broker_dir, items, user_ids) of
    the main cells' already-published SMALL catalog: a third door with
    ANN enabled proves measured-cost routing still serves the exact
    kernel where the catalog is too small for the streaming two-phase
    path ANN rides."""
    from ..app.als.ivf import AnnConfig
    cfg = AnnConfig(enabled=True, cells=cells, nprobe=nprobe,
                    min_recall=0.95, recall_at=50, recall_queries=64,
                    train_sample=min(items, 131072),
                    train_iterations=8)
    broker_dir = os.path.join(work_dir, "ann-broker")
    t0 = time.time()
    # components at cells/4: coarser than the partition, so k-means
    # over-segments every component instead of merging some (a merged
    # cell's averaged centroid falls out of the probe order and loses
    # its items wholesale — measured recall cliff)
    user_ids = _publish_model(broker_dir, users, items, features,
                              sharded=sharded, ann_cfg=cfg,
                              clustered=max(2, cells // 4))
    publish_s = round(time.time() - t0, 1)
    print(f"== ann probe: published {items} items (+index) in "
          f"{publish_s}s ==", file=sys.stderr)

    def _emulation(extra: dict, rows_streamed: float) -> None:
        # same pinning as run_cell: finite per-window capacity +
        # fixed pipeline depth make the emulated ceiling deterministic
        if device_ms_per_mrow <= 0:
            return
        extra.update({
            "oryx.serving.api.max-batch": 8,
            "oryx.serving.api.scoring-pipeline-depth": 2,
            "oryx.resilience.faults.serving-scan-dispatch"
            ".mode": "delay",
            "oryx.resilience.faults.serving-scan-dispatch"
            ".times": -1,
            "oryx.resilience.faults.serving-scan-dispatch"
            ".delay-ms": round(
                device_ms_per_mrow * rows_streamed / 1e6, 3),
        })

    ann_port, exact_port = _free_port(), _free_port()
    log_path = os.path.join(work_dir, "ann-probe.log")
    ann_keys = {
        "oryx.als.ann.enabled": True,
        "oryx.als.ann.cells": cells,
        "oryx.als.ann.nprobe": nprobe,
    }
    exact_extra: dict = {}
    _emulation(exact_extra, items)
    ann_extra = dict(ann_keys)
    _emulation(ann_extra, items * nprobe / cells)
    exact_conf = os.path.join(work_dir, "ann-exact-door.conf")
    ann_conf = os.path.join(work_dir, "ann-door.conf")
    _write_conf(exact_conf, broker_dir, exact_port, exact_extra)
    _write_conf(ann_conf, broker_dir, ann_port, ann_extra)

    def _door_metrics(port: int) -> tuple[dict, dict]:
        m = _get_json(port, "/metrics")
        return (m.get("freshness", {}),
                (m.get("model_metrics") or {}).get(
                    "kernel_route") or {})

    procs = [_spawn(["serving"], exact_conf, None, log_path),
             _spawn(["serving"], ann_conf, None, log_path)]
    try:
        for port in (exact_port, ann_port):
            _await(lambda p=port: _get_json(p, "/ready") is None,
                   "ann probe serving door", timeout=900.0)
        # first-touch scoring compiles per process; warm before any
        # rung (or spot answer) is judged.  The budget covers the
        # large-catalog model load still running behind /ready
        for port in (exact_port, ann_port):
            _get_json_retry_cold(
                port, f"/recommend/{user_ids[0]}?howMany=10",
                budget_sec=1200.0)

        # answer-quality spot-check: the ANN door may disagree with
        # the exact door within the recall budget (cell pruning is
        # what the certificate measures), so record top-10 overlap —
        # a door whose route stayed exact overlaps 1.0 exactly
        overlaps = []
        for uid in user_ids[:20]:
            got = [d["id"] for d in _get_json_retry_cold(
                ann_port, f"/recommend/{uid}?howMany=10")]
            want = [d["id"] for d in _get_json_retry_cold(
                exact_port, f"/recommend/{uid}?howMany=10")]
            overlaps.append(len(set(got) & set(want))
                            / max(1, len(want)))
        spot_overlap = round(sum(overlaps) / max(1, len(overlaps)), 4)
        answers_match = bool(overlaps) and min(overlaps) == 1.0

        def _ladder(port: int) -> tuple[list, dict | None]:
            ladder, best, rate = [], None, 1.0
            while rate <= 640.0:
                out = None
                for _attempt in range(2):
                    out = run_recommend_open_loop(
                        f"http://127.0.0.1:{port}", user_ids,
                        rate_qps=rate,
                        duration_sec=max(6.0, duration_sec),
                        workers=min(256, max(32, int(rate))))
                    if out["sustained"]:
                        break
                ladder.append(out)
                if out["sustained"]:
                    best = out
                else:
                    break
                rate = round(rate * 1.6, 1)
            return ladder, best

        # warm bursts compile the window ladder off the clock
        for port in (exact_port, ann_port):
            run_recommend_open_loop(
                f"http://127.0.0.1:{port}", user_ids, rate_qps=2.0,
                duration_sec=6.0, workers=16)
        exact_ladder, exact_best = _ladder(exact_port)
        ann_ladder, ann_best = _ladder(ann_port)
        exact_fresh, _ = _door_metrics(exact_port)
        ann_fresh, ann_route = _door_metrics(ann_port)
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=15)

    # routing control at the SMALL catalog: ANN enabled, yet the
    # measured route must keep serving the exact kernel family — the
    # catalog sits below the streaming threshold the IVF kind rides
    small_cell = None
    if small is not None:
        s_broker, s_items, s_users = small
        s_port = _free_port()
        s_conf = os.path.join(work_dir, "ann-small-door.conf")
        s_extra = dict(ann_keys)
        # cheap quantizer: this door exists to show the ROUTE, not to
        # certify recall at a size ANN never serves
        s_extra["oryx.als.ann.train-sample"] = max(cells, 16384)
        s_extra["oryx.als.ann.train-iterations"] = 2
        _write_conf(s_conf, s_broker, s_port, s_extra)
        proc = _spawn(["serving"], s_conf, None, log_path)
        try:
            _await(lambda: _get_json(s_port, "/ready") is None,
                   "ann probe small door", timeout=900.0)
            got = _get_json_retry_cold(
                s_port, f"/recommend/{s_users[0]}?howMany=10")
            s_fresh, s_route = _door_metrics(s_port)
            small_cell = {
                "items": s_items,
                "served": bool(got),
                "route_chosen": s_route.get("chosen"),
                "ivf_routed": s_route.get("chosen") == "ivf",
                "ann": s_route.get("ann"),
                "ann_index_fallbacks":
                    s_fresh.get("ann_index_fallbacks"),
            }
        finally:
            proc.kill()
            proc.wait(timeout=15)

    probe_fraction = round(nprobe / cells, 5)
    exact_qps = exact_best["achieved_qps"] if exact_best else 0.0
    ann_qps = ann_best["achieved_qps"] if ann_best else 0.0
    # the emulated probed-fraction delay assumes the ivf kind actually
    # serves: a door that fell back to the exact kernel (certificate
    # below min-recall, fail-closed index) under the THIN delay would
    # gate a number no real device produces — withhold the headline
    ivf_routed = ann_route.get("chosen") == "ivf"
    emulated = device_ms_per_mrow > 0
    headline_ok = ivf_routed or not emulated
    return {
        "items": items,
        "features": features,
        "users": users,
        "cells": cells,
        "nprobe": nprobe,
        "probe_fraction": probe_fraction,
        "publish_s": publish_s,
        "emulated_device_ms_per_mrow": device_ms_per_mrow,
        "emulated_exact_dispatch_ms": round(
            device_ms_per_mrow * items / 1e6, 3),
        "emulated_ann_dispatch_ms": round(
            device_ms_per_mrow * items * probe_fraction / 1e6, 3),
        "answers_match_exact": answers_match,
        "spot_overlap_at_10": spot_overlap,
        "catalog": "gaussian-mixture",
        # the gated headline: the ANN door's sustained qps, withheld
        # when the route never chose ivf under emulation (see above)
        "open_loop_sustained_qps": ann_qps if headline_ok else None,
        "ann_door_qps_raw": ann_qps,
        "ivf_routed": ivf_routed,
        "sustained_p50_ms": ann_best["p50_ms"] if ann_best else None,
        "sustained_p99_ms": ann_best["p99_ms"] if ann_best else None,
        "speedup_vs_exact": (round(ann_qps / exact_qps, 2)
                             if exact_qps and headline_ok else None),
        "certificate": ann_route.get("ann"),
        "route_chosen": ann_route.get("chosen"),
        "ann_model_load_s": ann_fresh.get("model_load_s"),
        "ann_index_bytes": ann_fresh.get("ann_index_bytes"),
        "ann_index_fallbacks": ann_fresh.get("ann_index_fallbacks"),
        "exact": {
            "open_loop_sustained_qps": exact_qps,
            "sustained_p50_ms":
                exact_best["p50_ms"] if exact_best else None,
            "sustained_p99_ms":
                exact_best["p99_ms"] if exact_best else None,
            "model_load_s": exact_fresh.get("model_load_s"),
            "ladder": exact_ladder,
        },
        "small_cell": small_cell,
        "ladder": ann_ladder,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", default="1,2,4",
                    help="comma list of replica counts")
    ap.add_argument("--items", type=int, default=524288,
                    help="catalog size; the default keeps every cell "
                         "(full, half, quarter catalog per replica) on "
                         "the SAME flat scan kernel family — a cell "
                         "ladder straddling the streaming threshold "
                         "would compare different kernels, not "
                         "replica counts")
    ap.add_argument("--features", type=int, default=129,
                    help="129 pads to the 256-lane device width: the "
                         "per-window scan cost of a 250-feature model "
                         "at roughly half the publish/replay bytes")
    ap.add_argument("--users", type=int, default=1000)
    ap.add_argument("--rates", default="",
                    help="explicit comma rate ladder (default: "
                         "geometric from 20)")
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--replica-threads", type=int, default=1,
                    help="XLA host compute threads per replica (fixed "
                         "per-replica hardware emulation)")
    ap.add_argument("--device-ms-per-mrow", type=float, default=0.0,
                    help="emulate a fixed-rate per-replica accelerator: "
                         "every scoring dispatch sleeps this many ms "
                         "per million catalog rows in the replica's "
                         "slice (no host CPU burned).  0 = off (scan "
                         "cost is the host CPU itself — only "
                         "meaningful when cores >> replicas)")
    ap.add_argument("--tracing-sample", type=float, default=None,
                    help="enable oryx.obs tracing on every process at "
                         "this sample ratio (e.g. 0.001 measures the "
                         "UNsampled per-request overhead, 1.0 records "
                         "every request).  Default: tracing off — the "
                         "shipped configuration")
    ap.add_argument("--replicas-per-shard", default="1",
                    help="comma list of group sizes R: each (replicas, "
                         "R) pair is a cell with R serving processes "
                         "per shard announcing the same (shard, of) — "
                         "the router load-balances, hedges, and fails "
                         "over within each group")
    ap.add_argument("--cells", default="",
                    help="explicit comma list of NxR cells (e.g. "
                         "1x1,1x2,2x1), overriding the "
                         "--replicas x --replicas-per-shard cross "
                         "product — a small box can measure 1x2 and "
                         "2x1 without the 2x2 cell's process count")
    ap.add_argument("--kill-probe", action="store_true",
                    help="in every R>1 cell, kill one group member "
                         "under steady load after the ladder and "
                         "record the kill-window availability "
                         "fraction (non-partial 200s)")
    ap.add_argument("--admission-max-inflight", type=int, default=0,
                    help="arm the router's admission hard cap on "
                         "concurrent data-plane requests (0 = off)")
    ap.add_argument("--admission-queue-wait-ms", type=int, default=0,
                    help="arm the router's measured-queue-wait shed "
                         "threshold in ms (0 = off)")
    ap.add_argument("--overload-factor", type=float, default=3.0,
                    help="overload rung rate = this x the cell's best "
                         "sustained qps (only when admission is "
                         "armed)")
    ap.add_argument("--admission-cells", default="",
                    help="comma list of NxR cells to arm admission "
                         "in (default: every cell when the admission "
                         "flags are set).  An armed cell's ladder "
                         "sheds near the ceiling, so keep the "
                         "regression-gated baseline cells un-gated — "
                         "exactly the configuration their previous "
                         "rounds ran")
    ap.add_argument("--cache", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="arm the router's exact result cache + "
                         "single-flight coalescing "
                         "(oryx.cluster.cache.* / coalesce.*).  The "
                         "uniform ladder flushes before every rung "
                         "and cache-busts every request so it stays a "
                         "cold/miss-path cell comparable with "
                         "pre-cache rounds; --no-cache reproduces "
                         "the pre-r11 router exactly")
    ap.add_argument("--zipf", type=float, default=0.0,
                    help="hot-user Zipf rung: rerun the rate ladder "
                         "with user picks drawn ∝ 1/rank^a (this "
                         "exponent), hit rate building across rungs — "
                         "the result cache's design load.  0 = off")
    ap.add_argument("--coalesce-burst", type=int, default=0,
                    help="single-flight rung: waves of this many "
                         "IDENTICAL concurrent requests against a "
                         "cold key — the herd must collapse to one "
                         "scatter (verdicts tallied).  0 = off")
    ap.add_argument("--async", dest="async_mode",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="run the router on the asyncio event-loop "
                         "front end (oryx.cluster.async.enabled); "
                         "--no-async reproduces the threaded r13 "
                         "router exactly")
    ap.add_argument("--transport",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="run the internal hop on the multiplexed "
                         "framed transport (one persistent connection "
                         "per replica); --no-transport falls back to "
                         "the HTTP/1.1 socket pool")
    ap.add_argument("--replica-cache",
                    action=argparse.BooleanOptionalAction,
                    default=False,
                    help="arm the replica-side result cache "
                         "(oryx.cluster.replica-cache.enabled) on "
                         "every replica — repeated identical shard "
                         "queries under an unchanged model epoch skip "
                         "the device.  Off by default so the "
                         "uniform-cold cell stays an honest miss-path "
                         "measurement")
    ap.add_argument("--connections", default="",
                    help="comma ladder of concurrent keep-alive "
                         "socket counts (e.g. 256,1024,4096): each "
                         "rung drives the cache-hit workload through "
                         "that many sockets and records open-socket + "
                         "router-thread telemetry; the top rung gates "
                         "as the (..., 'conns') pseudo-cell.  Empty = "
                         "off")
    ap.add_argument("--sharded-publish", type=int, default=24,
                    help="publish the model as this many murmur2 "
                         "slices + a manifest-carrying MODEL-REF (no "
                         "per-row UP flood) so replicas bulk-load "
                         "O(catalog/N); each cell records per-replica "
                         "model_load_s/slice bytes, gated by "
                         "check_regression as the (..., 'load') "
                         "pseudo-cell.  0 = the pre-r12 full-stream "
                         "replay publish")
    ap.add_argument("--regions", type=int, default=1,
                    help="2 = run the two-region mirror probe before "
                         "the qps cells: steady-state "
                         "cross_region_staleness_ms and "
                         "healed-partition catch-up over a real "
                         "mirror process + file:// brokers, gated by "
                         "check_regression as the (..., 'mirror') "
                         "pseudo-cell on catch-up records/s")
    ap.add_argument("--mirror-records", type=int, default=2000,
                    help="backlog size the mirror probe's healed "
                         "partition must catch up through")
    ap.add_argument("--write-heavy", action="store_true",
                    help="before the qps cells, run the durable-ack "
                         "write rung (ISSUE 17): a real serving door "
                         "+ speed worker, an open-loop POST /pref "
                         "ladder to the highest sustained ACKED "
                         "writes/s, a tight-gate overload burst, and "
                         "the acked==durable==folded-once ledger")
    ap.add_argument("--write-rates", default="",
                    help="comma list of offered write rates for the "
                         "write-heavy ladder (default 150..2400 "
                         "doubling)")
    ap.add_argument("--ann", action="store_true",
                    help="after the qps cells' publish, run the "
                         "IVF-ANN rung (ISSUE 18): one large-catalog "
                         "generation published WITH per-slice index "
                         "artifacts, an ANN-enabled door laddered "
                         "against an exact door on the same "
                         "generation (device emulation scales the "
                         "ANN dispatch by the probed fraction "
                         "nprobe/cells), the recall certificate read "
                         "off /metrics, plus a small-catalog control "
                         "door proving routing still picks the exact "
                         "kernel there; gated by check_regression as "
                         "the (..., 'ann') pseudo-cell")
    ap.add_argument("--ann-items", type=int, default=10_000_000,
                    help="ANN rung catalog size.  The protocol cell "
                         "is 10M items (a >=100M-rating generation's "
                         "catalog); on a small shared box run a "
                         "feasible size (e.g. 1048576) — the artifact "
                         "records what actually ran")
    ap.add_argument("--ann-cells", type=int, default=1024,
                    help="IVF coarse-quantizer cell count for the ANN "
                         "rung")
    ap.add_argument("--ann-nprobe", type=int, default=32,
                    help="cells probed per query on the ANN rung "
                         "(probed fraction = nprobe/cells)")
    ap.add_argument("--load-compare", type=int, default=0,
                    help="before the qps cells, publish the catalog "
                         "BOTH ways and boot this many shards against "
                         "each, recording replay vs sliced load times "
                         "and their ratio (the O(catalog/N) "
                         "acceptance evidence).  0 = off")
    ap.add_argument("--out", default="BENCH_GATEWAY_r15.json")
    ap.add_argument("--keep-work", action="store_true")
    args = ap.parse_args(argv)

    if args.rates:
        rates = [float(r) for r in args.rates.split(",")]
    else:
        rates, r = [], 20.0
        while r <= 4000.0:
            rates.append(round(r))
            r *= 1.35

    work_dir = tempfile.mkdtemp(prefix="oryx-gw-bench-")
    rows = []
    try:
        # one shared broker/model stream: every cell's replicas replay
        # the identical totally-ordered topic (cells run sequentially;
        # dead cells' heartbeats age out past the TTL)
        mirror_probe = None
        if args.regions >= 2:
            print("== two-region mirror probe ==", file=sys.stderr)
            mirror_probe = run_mirror_probe(
                work_dir, records=args.mirror_records)
            print(json.dumps(mirror_probe), file=sys.stderr)
        write_probe = None
        if args.write_heavy:
            print("== write-heavy probe (durable-ack ingest) ==",
                  file=sys.stderr)
            write_probe = run_write_heavy_probe(
                work_dir,
                rates=[float(r) for r in args.write_rates.split(",")
                       if r] or None)
            print(json.dumps(write_probe), file=sys.stderr)
        load_compare = None
        if args.load_compare > 0:
            print("== load-compare probe (replay vs sliced) ==",
                  file=sys.stderr)
            load_compare = run_load_compare(
                work_dir, args.items, args.features, args.users,
                args.load_compare, args.replica_threads,
                args.sharded_publish or 24)
            print(json.dumps(load_compare), file=sys.stderr)
        broker_dir = os.path.join(work_dir, "broker")
        os.makedirs(broker_dir, exist_ok=True)
        t0 = time.time()
        user_ids = _publish_model(broker_dir, args.users, args.items,
                                  args.features,
                                  sharded=args.sharded_publish)
        publish_s = round(time.time() - t0, 1)
        print(f"== published model stream in {publish_s}s ==",
              file=sys.stderr)
        ann_probe = None
        if args.ann:
            print("== ann probe (IVF vs exact, large catalog) ==",
                  file=sys.stderr)
            ann_probe = run_ann_probe(
                work_dir, args.ann_items, args.features, args.users,
                args.duration,
                device_ms_per_mrow=args.device_ms_per_mrow,
                cells=args.ann_cells, nprobe=args.ann_nprobe,
                sharded=args.sharded_publish or 24,
                small=(broker_dir, args.items, user_ids))
            print(json.dumps({k: v for k, v in ann_probe.items()
                              if k not in ("ladder", "exact")}),
                  file=sys.stderr)
        admission = {}
        if args.admission_max_inflight > 0:
            admission["oryx.cluster.admission.max-inflight"] = \
                args.admission_max_inflight
        if args.admission_queue_wait_ms > 0:
            admission["oryx.cluster.admission.queue-wait-high-ms"] = \
                args.admission_queue_wait_ms
        if args.cells:
            cells = [tuple(int(v) for v in c.split("x"))
                     for c in args.cells.split(",") if c]
        else:
            group_sizes = [int(x) for x in
                           args.replicas_per_shard.split(",") if x]
            cells = [(n, rps)
                     for n in [int(x) for x in
                               args.replicas.split(",") if x]
                     for rps in group_sizes]
        admission_cells = {
            tuple(int(v) for v in c.split("x"))
            for c in args.admission_cells.split(",") if c}
        for n, rps in cells:
            print(f"== cell: {n} shard(s) x {rps} member(s) ==",
                  file=sys.stderr)
            cell_admission = admission or None
            if admission_cells and (n, rps) not in admission_cells:
                cell_admission = None
            row = run_cell(
                n, args.items, args.features, args.users, rates,
                args.duration, args.replica_threads, work_dir,
                broker_dir=broker_dir, user_ids=user_ids,
                device_ms_per_mrow=args.device_ms_per_mrow,
                tracing_sample=args.tracing_sample,
                replicas_per_shard=rps,
                kill_member_probe=args.kill_probe,
                admission=cell_admission,
                overload_factor=args.overload_factor,
                cache=args.cache,
                zipf=args.zipf,
                coalesce_burst=args.coalesce_burst,
                sharded_publish=args.sharded_publish,
                async_mode=args.async_mode,
                transport=args.transport,
                replica_cache=args.replica_cache,
                connections=[int(x) for x in
                             args.connections.split(",") if x])
            row["publish_s"] = publish_s
            if mirror_probe is not None and not rows:
                # the probe rides the FIRST row as its (..., "mirror")
                # pseudo-cell — one measurement per round, one gate
                row["mirror"] = mirror_probe
            if write_probe is not None and not rows:
                # same shape: the write-heavy rung rides the first row
                # as the (..., "writes") pseudo-cell
                row["writes"] = write_probe
            if ann_probe is not None and not rows:
                # and the IVF-ANN rung as the (..., "ann") pseudo-cell
                row["ann"] = ann_probe
            rows.append(row)
            print(json.dumps({k: v for k, v in rows[-1].items()
                              if k != "ladder"}), file=sys.stderr)
    finally:
        if not args.keep_work:
            shutil.rmtree(work_dir, ignore_errors=True)

    # shard-scaling summary compares like-for-like R=1 cells only;
    # replica groups add availability, not shard-scaling
    by_n = {r["replicas"]: r["open_loop_sustained_qps"]
            for r in rows if r["replicas_per_shard"] == 1}
    report = {
        "metric": "gateway_recommend_scaling",
        "cache_armed": args.cache,
        "async_front_end": args.async_mode,
        "framed_transport": args.transport,
        "replica_cache_armed": args.replica_cache,
        "connections": args.connections or None,
        "sharded_publish": args.sharded_publish or None,
        "load_compare": load_compare,
        "regions": args.regions,
        "mirror_probe": mirror_probe,
        "write_probe": write_probe,
        "ann_probe": ann_probe,
        "zipf_a": args.zipf or None,
        "tracing_sample": args.tracing_sample,
        "emulated_device_ms_per_mrow": args.device_ms_per_mrow,
        "backend": "cpu" if os.environ.get(
            "JAX_PLATFORMS", "cpu") == "cpu" else "tpu",
        "host_cpus": os.cpu_count(),
        "rows": rows,
        "scaling_vs_1": {
            str(n): round(q / by_n[1], 2)
            for n, q in sorted(by_n.items()) if 1 in by_n and by_n[1]},
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items() if k != "rows"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
