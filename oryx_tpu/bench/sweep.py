"""Hyperparameter sweep through MLUpdate's candidate search at bench
scale — P2 (model-selection parallelism) exercised where it matters.

Reference: MLUpdate.java:254-296 builds `candidates` models over the
hyperparameter combos (HyperParams.java:74-196) on a parallel stream,
evaluates each on the held-out split, and atomically publishes the best.
This bench drives the repo's real `ALSUpdate.run_update` loop (not a
shortcut) over a features x lambda grid on MovieLens-format data (real
files via $ORYX_ML_DATA / --data, synthetic fallback at the same shape)
and records every candidate's eval plus the one the search published —
gating that the published model IS the argmax.

Usage: python -m oryx_tpu.bench.sweep [--ratings 2000000]
       [--data /path/to/ml-20m] [--out BENCH_TRAIN_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np

from ..common import pmml as pmml_io
from ..common.config import from_dict
from ..kafka.api import KeyMessage
from .datasets import movielens_or_synthetic

__all__ = ["run_sweep"]


def run_sweep(ratings: int = 2_000_000, data_path: str | None = None,
              features_grid=(20, 60), lambda_grid=(0.0005, 0.05),
              iterations: int = 6, seed: int = 7,
              n_users: int | None = None,
              n_items: int | None = None) -> dict:
    users, items, values, user_ids, item_ids, source = \
        movielens_or_synthetic(data_path, ratings, seed,
                               n_users=n_users, n_items=n_items)

    t0 = time.perf_counter()
    # the real ingestion surface: CSV input lines, exactly what the
    # batch layer hands MLUpdate (MLFunctions.PARSE_FN wire format)
    # increasing timestamps: ALSUpdate's train/test split is TIME-based
    # (newest fraction becomes test, update.py split_new_data_to_train_
    # test), so the wire events need a time order
    ts = 1_700_000_000_000
    msgs = [KeyMessage(None, f"{user_ids[u]},{item_ids[i]},{v:.2f},{ts + j}")
            for j, (u, i, v) in enumerate(zip(users.tolist(),
                                              items.tolist(),
                                              np.round(values,
                                                       2).tolist()))]
    encode_s = time.perf_counter() - t0

    from ..app.als.update import ALSUpdate

    evals: list[dict] = []

    class RecordingALSUpdate(ALSUpdate):
        def evaluate(self, model, candidate_path, test_data, train_data):
            e = super().evaluate(model, candidate_path, test_data,
                                 train_data)
            rescue = pmml_io.get_extension_value(model, "rescue")
            evals.append({
                "features": int(pmml_io.get_extension_value(model,
                                                            "features")),
                "lambda": float(pmml_io.get_extension_value(model,
                                                            "lambda")),
                "eval": float(e),
                # which rescue rung (if any) trained this candidate:
                # None = clean f32, else {precision, trigger_iteration,
                # escalated_lambda}
                "rescue": json.loads(rescue) if rescue else None,
            })
            return e

    n_candidates = len(features_grid) * len(lambda_grid)
    with tempfile.TemporaryDirectory() as td:
        cfg = from_dict({
            "oryx.als.implicit": False,
            "oryx.als.iterations": iterations,
            "oryx.als.hyperparams.features": list(features_grid),
            "oryx.als.hyperparams.lambda": list(lambda_grid),
            "oryx.ml.eval.candidates": n_candidates,
            "oryx.ml.eval.parallelism": 2,
            "oryx.ml.eval.test-fraction": 0.1,
            
        })
        upd = RecordingALSUpdate(cfg)
        t0 = time.perf_counter()
        upd.run_update(int(time.time() * 1000), msgs, [], td, None)
        sweep_s = time.perf_counter() - t0

        published = [d for d in os.listdir(td) if d.isdigit()]
        assert len(published) == 1, published
        from ..ml.mlupdate import MODEL_FILE_NAME
        doc = pmml_io.read(os.path.join(td, published[0], MODEL_FILE_NAME))
        chosen = {
            "features": int(pmml_io.get_extension_value(doc, "features")),
            "lambda": float(pmml_io.get_extension_value(doc, "lambda")),
        }

    # The rescue ladder (f32 -> f64 -> escalated lambda) means EVERY
    # candidate of the reference's grid trains — 0 NaN evals is the
    # gate (MLlib trains f64 at lambda=5e-4; pre-rescue the f32 path
    # diverged there and half the grid was lost).  Argmax is over the
    # finite evals; each candidate records the rescue rung it needed.
    finite = [d for d in evals if d["eval"] == d["eval"]]
    nan_candidates = len(evals) - len(finite)
    # candidates that never reached evaluate() at all (diverged beyond
    # rescue, or rejected by the pre-publish gate) are just as lost as
    # NaN ones — the 0-NaN acceptance gate must count them too
    missing_candidates = n_candidates - len(evals)
    best = max(finite, key=lambda d: d["eval"]) if finite else None
    gate_ok = (best is not None
               and chosen["features"] == best["features"]
               and chosen["lambda"] == best["lambda"]
               and len(evals) == n_candidates)
    rescued = [d for d in evals if d.get("rescue")]
    return {
        "metric": "als_hyperparam_sweep",
        "dataset": source,
        "ratings": int(len(msgs)),
        "grid": {"features": list(features_grid),
                 "lambda": list(lambda_grid)},
        "candidates": evals,
        "chosen": chosen,
        "eval_metric": "-RMSE (explicit; Evaluation.java:49-63 semantics)",
        "published_is_argmax": gate_ok,
        "nan_candidates": nan_candidates,
        "missing_candidates": missing_candidates,
        "all_candidates_trained": (nan_candidates == 0
                                   and missing_candidates == 0),
        "rescued_candidates": len(rescued),
        "rescues": {
            "float64": sum(1 for d in rescued
                           if d["rescue"].get("escalated_lambda") is None),
            "escalated_lambda": sum(
                1 for d in rescued
                if d["rescue"].get("escalated_lambda") is not None),
        },
        "eval_parallelism": 2,
        "sweep_wall_s": round(sweep_s, 1),
        "csv_encode_s": round(encode_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ratings", type=int, default=2_000_000)
    ap.add_argument("--data", default=None)
    ap.add_argument("--iterations", type=int, default=6)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    result = run_sweep(ratings=args.ratings, data_path=args.data,
                       iterations=args.iterations)
    import jax

    result["device"] = str(jax.devices()[0].platform)
    assert result["published_is_argmax"], result
    # ISSUE 2 acceptance gate: every grid candidate (including the
    # lambda=5e-4 half MLlib can train and f32-only could not) trained
    assert result["all_candidates_trained"], result
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
