"""Serving load benchmark: a synthetic ALS model served over live HTTP,
driven by concurrent /recommend clients.

Reference: app/oryx-app-serving/src/test/java/.../als/LoadBenchmark.java:65
(opt-in benchmark profile: build a LoadTestALSModelFactory model with
configurable users/items/features/lshSampleRate/workers, fire
/recommend requests, log mean req time + heap) and
LoadTestALSModelFactory.java:34.

The factory sets vectors in bulk through the same set_user_vector /
set_item_vector path the update-topic replay uses, so benchmarked state
is the state production reaches.
"""

from __future__ import annotations

import dataclasses
import http.client
import logging
import math
import threading
import time
import urllib.parse

import numpy as np

from ..api.serving import ServingModelManager
from ..app.als.serving_model import ALSServingModel
from ..common.rand import RandomManager

_log = logging.getLogger(__name__)

__all__ = ["StaticModelManager", "build_load_test_model", "LoadStats",
           "run_recommend_load", "run_recommend_open_loop",
           "zipf_picks"]


class StaticModelManager(ServingModelManager):
    """Read-only manager serving a prebuilt model, for load benches and
    endpoint tests (reference test scope: MockServingModelManager.java:27).
    Subclass per test and set the ``model`` class attribute."""

    model = None

    def __init__(self, config=None):
        pass

    def consume(self, updates) -> None:
        pass

    def get_model(self):
        return type(self).model

    def is_read_only(self) -> bool:
        return True


def build_load_test_model(users: int = 10_000, items: int = 50_000,
                          features: int = 50,
                          lsh_sample_rate: float = 1.0,
                          known_items_per_user: int = 9) -> ALSServingModel:
    """Synthetic ALS serving model (reference:
    LoadTestALSModelFactory.java:34 — default 2M x 9.7M x 250 on a
    32-core box; scale down by default for laptop-class runs)."""
    rng = RandomManager.random()
    model = ALSServingModel(features, implicit=True,
                            sample_rate=lsh_sample_rate)
    t0 = time.time()
    x = rng.standard_normal((users, features)).astype(np.float32)
    y = rng.standard_normal((items, features)).astype(np.float32)
    user_ids = [str(u) for u in range(users)]
    item_ids = [str(i) for i in range(items)]
    for u, uid in enumerate(user_ids):
        model.set_user_vector(uid, x[u])
        if known_items_per_user:
            known = rng.integers(0, items, known_items_per_user)
            model.add_known_items(uid, [item_ids[k] for k in known])
    for i, iid in enumerate(item_ids):
        model.set_item_vector(iid, y[i])
    _log.info("Built load-test model %dx%dx%d in %.1fs",
              users, items, features, time.time() - t0)
    return model


@dataclasses.dataclass
class LoadStats:
    requests: int
    errors: int
    elapsed_sec: float
    latencies_ms: np.ndarray

    @property
    def qps(self) -> float:
        return self.requests / self.elapsed_sec if self.elapsed_sec else 0.0

    def percentile_ms(self, p: float) -> float:
        return float(np.percentile(self.latencies_ms, p)) \
            if len(self.latencies_ms) else float("nan")

    def summary(self) -> dict:
        return {
            "requests": self.requests,
            "errors": self.errors,
            "qps": round(self.qps, 2),
            "p50_ms": round(self.percentile_ms(50), 3),
            "p95_ms": round(self.percentile_ms(95), 3),
            "p99_ms": round(self.percentile_ms(99), 3),
        }


def run_recommend_load(base_url: str, user_ids: list[str],
                       requests: int = 1000, workers: int = 4,
                       how_many: int = 10,
                       timeout_sec: float = 30.0) -> LoadStats:
    """Drive GET /recommend/{user} with ``workers`` concurrent clients
    (reference: LoadBenchmark.java uses ExecUtils.doInParallel over a
    worker count; 1-3 concurrent requests saturate the scorer)."""
    rng = RandomManager.random()
    picks = rng.integers(0, len(user_ids), requests)
    latencies: list[float] = []
    errors = [0]
    lock = threading.Lock()
    next_index = [0]
    parsed = urllib.parse.urlparse(base_url)
    host, port = parsed.hostname, parsed.port
    path_prefix = parsed.path.rstrip("/")

    def worker():
        # one persistent keep-alive connection per worker, driven with a
        # hand-rolled HTTP/1.1 client: http.client routes every response
        # through the email-parser machinery, and with client and server
        # sharing host cores that parsing shows up as lost server qps —
        # the harness must not be the bottleneck it is measuring
        import socket

        conn = rfile = None

        def connect():
            nonlocal conn, rfile
            conn = socket.create_connection((host, port),
                                            timeout=timeout_sec)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            rfile = conn.makefile("rb")

        def one(path: str) -> bool:
            conn.sendall(f"GET {path} HTTP/1.1\r\nHost: a\r\n\r\n"
                         .encode("latin-1"))
            status_line = rfile.readline(65537)
            if not status_line:
                raise ConnectionError("closed")
            status = int(status_line.split(b" ", 2)[1])
            clen = 0
            while True:
                h = rfile.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                if h[:15].lower() == b"content-length:":
                    clen = int(h[15:])
            if clen:
                remaining = clen
                while remaining:
                    got = rfile.read(remaining)
                    if not got:
                        raise ConnectionError("short body")
                    remaining -= len(got)
            return status == 200

        try:
            while True:
                with lock:
                    i = next_index[0]
                    if i >= requests:
                        return
                    next_index[0] += 1
                path = (f"{path_prefix}/recommend/{user_ids[picks[i]]}"
                        f"?howMany={how_many}")
                start = time.perf_counter()
                try:
                    if conn is None:
                        connect()  # lazy/retried, like http.client did
                    ok = one(path)
                except Exception:
                    ok = False
                    if conn is not None:
                        try:
                            conn.close()
                        except OSError:
                            pass
                        conn = None  # reconnect on next request
                ms = (time.perf_counter() - start) * 1000.0
                with lock:
                    if ok:
                        latencies.append(ms)
                    else:
                        errors[0] += 1
        finally:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return LoadStats(requests=len(latencies), errors=errors[0],
                     elapsed_sec=elapsed,
                     latencies_ms=np.asarray(latencies))


def zipf_picks(rng, n_users: int, n: int, a: float) -> np.ndarray:
    """Rank-frequency Zipf draw over the user population: user at rank
    r is drawn with probability ∝ 1/r^a — the hot-user skew real
    recommendation traffic shows, and the shape the router's exact
    result cache is built to exploit."""
    ranks = np.arange(1, n_users + 1, dtype=np.float64)
    p = 1.0 / np.power(ranks, a)
    p /= p.sum()
    return rng.choice(n_users, size=n, p=p)


def run_recommend_open_loop(base_url: str, user_ids: list[str],
                            rate_qps: float, duration_sec: float = 6.0,
                            workers: int = 512, how_many: int = 10,
                            timeout_sec: float = 30.0,
                            zipf_a: float | None = None,
                            cache_bust: bool = False) -> dict:
    """OPEN-LOOP /recommend driver: requests arrive on an exponential
    inter-arrival schedule at ``rate_qps`` regardless of responses, and
    latency is measured from the SCHEDULED arrival time — so queueing
    delay when the server falls behind counts against it (reference:
    TrafficUtil.java:63, exponential inter-arrival against live hosts).
    A closed-loop client bounded by transport RTT measures the
    transport; this measures the server.  Saturation shows as achieved
    qps below offered and a growing scheduled-to-completion tail.

    ``zipf_a`` skews the user draw hot-user-Zipf instead of uniform;
    per-response ``X-Oryx-Cache`` verdicts are tallied (with a hit-only
    latency split) whenever the router stamps them.  ``cache_bust``
    appends a unique query arg per request so every request is a
    distinct cache key — the honest way to measure the MISS path
    against a cache-armed router (uniform draws repeat users within a
    rung past ~sqrt(2·users) requests, and those accidental hits would
    inflate a 'cold' cell)."""
    rng = RandomManager.random()
    n = max(1, int(rate_qps * duration_sec))
    arrivals = np.cumsum(rng.exponential(1.0 / rate_qps, n))
    picks = zipf_picks(rng, len(user_ids), n, zipf_a) \
        if zipf_a else rng.integers(0, len(user_ids), n)
    parsed = urllib.parse.urlparse(base_url)
    host, port = parsed.hostname, parsed.port
    path_prefix = parsed.path.rstrip("/")
    latencies: list[float] = []
    lateness: list[float] = []
    done_ts: list[float] = []
    # (latency_ms, X-Oryx-Trace id) for sampled responses: lets the
    # harness name the recorded trace behind each worst-p99 request
    traced: list[tuple[float, str]] = []
    # X-Oryx-Cache verdict tallies + hit-only latencies (the cached-hit
    # p50 headline); empty when the router does not stamp the header
    cache_counts: dict[str, int] = {}
    hit_lat: list[float] = []
    errors = [0]
    lock = threading.Lock()
    next_index = [0]
    t0 = time.perf_counter()

    def worker():
        import socket

        conn = rfile = None

        def connect():
            nonlocal conn, rfile
            conn = socket.create_connection((host, port),
                                            timeout=timeout_sec)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            rfile = conn.makefile("rb")

        def one(path: str) -> tuple[bool, str | None, str | None]:
            conn.sendall(f"GET {path} HTTP/1.1\r\nHost: a\r\n\r\n"
                         .encode("latin-1"))
            status_line = rfile.readline(65537)
            if not status_line:
                raise ConnectionError("closed")
            status = int(status_line.split(b" ", 2)[1])
            clen = 0
            trace = verdict = None
            while True:
                h = rfile.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                if h[:15].lower() == b"content-length:":
                    clen = int(h[15:])
                elif h[:13].lower() == b"x-oryx-trace:":
                    trace = h[13:].strip().decode("latin-1")
                elif h[:13].lower() == b"x-oryx-cache:":
                    verdict = h[13:].strip().decode("latin-1")
            if clen:
                remaining = clen
                while remaining:
                    got = rfile.read(remaining)
                    if not got:
                        raise ConnectionError("short body")
                    remaining -= len(got)
            return status == 200, trace, verdict

        try:
            while True:
                with lock:
                    i = next_index[0]
                    if i >= n:
                        return
                    next_index[0] += 1
                scheduled = t0 + arrivals[i]
                now = time.perf_counter()
                if scheduled > now:
                    time.sleep(scheduled - now)
                late = max(0.0, time.perf_counter() - scheduled)
                path = (f"{path_prefix}/recommend/{user_ids[picks[i]]}"
                        f"?howMany={how_many}")
                if cache_bust:
                    path += f"&cb={i}"
                trace = verdict = None
                sent = None
                try:
                    if conn is None:
                        connect()
                    # stamped AFTER the (re)connect: a hit's recorded
                    # latency must name the server's cost, not a
                    # post-error TCP handshake on this worker's socket
                    sent = time.perf_counter()
                    ok, trace, verdict = one(path)
                except Exception:  # noqa: BLE001 — counted as error
                    ok = False
                    if conn is not None:
                        try:
                            conn.close()
                        except OSError:
                            pass
                        conn = None
                done = time.perf_counter()
                ms = (done - scheduled) * 1000.0
                with lock:
                    lateness.append(late * 1000.0)
                    if ok:
                        latencies.append(ms)
                        done_ts.append(done - t0)
                        if trace:
                            traced.append((ms, trace))
                        if verdict:
                            cache_counts[verdict] = \
                                cache_counts.get(verdict, 0) + 1
                            if verdict == "hit" and sent is not None:
                                # send->response latency, NOT schedule
                                # slip: the cached-hit p50 must name
                                # the server's cost, not client-pool
                                # queueing at rates past the cold
                                # ceiling
                                hit_lat.append((done - sent) * 1000.0)
                    else:
                        errors[0] += 1
        finally:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lat = np.asarray(latencies)
    # achieved = completion throughput over a MID WINDOW of the
    # scheduled span ([15%, 90%)).  Total-count-over-wall-time folds
    # the last requests' drain tail into the denominator (a ~14%
    # structural under-report at 0.3 s latencies);
    # total-count-over-scheduled-span is tautologically == offered
    # whenever nothing errors (the fixed worker pool completes every
    # request eventually).  The window excludes both ramp-in and
    # drain: at a sustained rate it measures the offered rate, in
    # overload it measures the server's service capacity.
    span = float(arrivals[-1])
    dt = np.asarray(done_ts)
    w0, w1 = 0.15 * span, 0.9 * span
    mid_done = int(((dt >= w0) & (dt < w1)).sum()) if span else 0
    mid_arr = int(((arrivals >= w0) & (arrivals < w1)).sum()) \
        if span else 0
    achieved = mid_done / (w1 - w0) if span else 0.0
    # kept-up gate: in-window completions vs in-window SCHEDULED
    # arrivals.  Comparing completions against offered*window instead
    # would re-introduce the arrival process's own Poisson noise
    # (relative std 1/sqrt(count): ~14% at a 25 qps x 6 s rung — a
    # healthy server would fail such rungs ~1/3 of the time); against
    # in-window arrivals the arrival noise cancels at stationarity,
    # leaving boundary jitter, absorbed by a 2*sqrt Poisson allowance.
    # Resolution limit: a rung can only resolve overload coarser than
    # max(5%, 2/sqrt(arrivals-in-window)).
    allowance = max(0.05 * mid_arr, 2.0 * math.sqrt(mid_arr))
    kept_up = (mid_done >= mid_arr - allowance) if mid_arr \
        else len(latencies) == n
    late = np.asarray(lateness)
    # saturation = the backlog GROWS across the run: compare mean
    # scheduled-lateness of the third quarter vs the final quarter of
    # arrivals; steady lateness (client pool + transport slack) is
    # fine, divergence is not.  Secondary signal alongside kept_up.
    n_l = len(late)
    growing = False
    if n_l >= 8:
        q3 = float(np.mean(late[n_l // 2:3 * n_l // 4]))
        q4 = float(np.mean(late[3 * n_l // 4:]))
        growing = q4 > q3 + 200.0  # ms of drift across ~1/4 of the run
    # worst sampled requests, slowest first: each X-Oryx-Trace id names
    # a recorded span tree on /admin/traces, so a bad p99 here is
    # directly attributable (queue-wait vs device-execute vs merge)
    worst = [{"ms": round(ms, 1), "trace": t}
             for ms, t in sorted(traced, reverse=True)[:5]]
    stamped = sum(cache_counts.values())
    cache = None
    if stamped:
        cache = dict(cache_counts)
        cache["hit_rate"] = round(
            cache_counts.get("hit", 0) / stamped, 4)
        if hit_lat:
            hl = np.asarray(hit_lat)
            cache["hit_p50_ms"] = round(float(np.percentile(hl, 50)), 3)
            cache["hit_p99_ms"] = round(float(np.percentile(hl, 99)), 3)
    return {
        "offered_qps": round(rate_qps, 1),
        "achieved_qps": round(achieved, 1),
        "errors": errors[0],
        "worst_sampled": worst,
        "cache": cache,
        "p50_ms": round(float(np.percentile(lat, 50)), 1) if len(lat) else None,
        "p95_ms": round(float(np.percentile(lat, 95)), 1) if len(lat) else None,
        "p99_ms": round(float(np.percentile(lat, 99)), 1) if len(lat) else None,
        # mean time requests spent waiting for a free client slot past
        # their scheduled arrival — the open-loop backlog signal
        "mean_sched_lateness_ms": round(float(np.mean(late)), 1)
        if n_l else None,
        "lateness_drift_ms": round(q4 - q3, 1) if n_l >= 8 else None,
        "mid_window": {"arrivals": mid_arr, "completions": mid_done},
        "sustained": errors[0] == 0 and not growing and kept_up,
    }
