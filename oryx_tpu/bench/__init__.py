"""Load/perf harnesses: synthetic model factory, /recommend load
benchmark, and the standalone HTTP traffic generator (reference tier-4
test strategy: LoadBenchmark.java, LoadTestALSModelFactory.java,
TrafficUtil.java)."""
