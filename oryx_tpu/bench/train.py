"""North-star training benchmark: MovieLens-20M-scale ALS on TPU.

BASELINE.md names the north-star metric explicitly: "ALS epoch time +
test RMSE on MovieLens-20M rank=100" (the reference defers batch-layer
performance to Spark MLlib — docs/docs/performance.html "Batch Layer").
There is no network egress in this environment, so the dataset is
synthesized at MovieLens-20M shape (138,493 users x 26,744 items x 20M
interactions, power-law popularity and user activity) WITH planted
latent structure, so the held-out quality numbers are a real gate:

 - implicit run: item selection is driven by per-user latent cluster
   preferences; a correct rank-100 implicit ALS must push held-out
   per-user AUC (Evaluation.java:70-136 semantics) far above 0.5.
 - explicit run: ratings are true-factor dot products + N(0, sigma)
   noise clipped to the 0.5..5 star scale; a correct solver drives
   held-out RMSE (Evaluation.java:49-63 semantics) toward sigma.

Epoch time = wall time of one full alternating sweep (both halves) on
the device, measured after the compile-warm first sweep.

Usage:  python -m oryx_tpu.bench.train [--ratings 20000000 --rank 100]
Prints one JSON line; also writes the artifact file when --out is given.
"""

from __future__ import annotations

import argparse
import json
import math
import time

import numpy as np

from ..app.als.common import ParsedRatings
from ..app.als.evaluation import area_under_curve, rmse
from ..app.als.trainer import train_als

__all__ = ["synthesize_movielens", "run_training_bench"]

ML20M_USERS = 138_493
ML20M_ITEMS = 26_744
ML20M_RATINGS = 20_000_000


def _sample_from_cdf(rng: np.random.Generator, cdf: np.ndarray,
                     n: int) -> np.ndarray:
    # float cumsum can leave cdf[-1] slightly below 1.0; clamp so a draw
    # above it cannot index one past the end
    idx = np.searchsorted(cdf, rng.random(n), side="right")
    return np.minimum(idx, len(cdf) - 1).astype(np.int32)


def synthesize_movielens(n_users: int = ML20M_USERS,
                         n_items: int = ML20M_ITEMS,
                         n_ratings: int = ML20M_RATINGS,
                         n_clusters: int = 96,
                         latent_rank: int = 12,
                         noise_sigma: float = 0.5,
                         seed: int = 7):
    """MovieLens-shaped interactions with planted latent structure.

    Returns (users, items, implicit_values, explicit_values, noise_sigma)
    as deduplicated COO arrays in index space.  Item popularity and user
    activity are power-law; each user belongs to a preference cluster and
    85% of their interactions come from that cluster's item distribution
    (that is the structure implicit ALS must recover).  Explicit values
    are true-factor dots + gaussian noise on the 0.5..5 star scale.
    """
    rng = np.random.default_rng(seed)

    # power-law global item popularity and user activity
    item_pop = 1.0 / np.power(np.arange(1, n_items + 1), 0.8)
    rng.shuffle(item_pop)
    item_cdf = np.cumsum(item_pop / item_pop.sum())
    user_act = np.exp(rng.normal(0.0, 1.0, n_users))
    user_cdf = np.cumsum(user_act / user_act.sum())

    users = _sample_from_cdf(rng, user_cdf, n_ratings)

    # per-cluster item distributions: popularity reshaped by lognormal
    # affinity noise -> clusters concentrate on different item subsets
    user_cluster = rng.integers(0, n_clusters, n_users).astype(np.int32)
    items = np.empty(n_ratings, dtype=np.int32)
    from_cluster = rng.random(n_ratings) < 0.85
    n_global = int(np.count_nonzero(~from_cluster))
    items[~from_cluster] = _sample_from_cdf(rng, item_cdf, n_global)
    rating_cluster = user_cluster[users]
    for c in range(n_clusters):
        mask = from_cluster & (rating_cluster == c)
        m = int(np.count_nonzero(mask))
        if m == 0:
            continue
        affinity = item_pop * np.exp(
            np.random.default_rng(seed * 1000 + c).normal(0.0, 2.0, n_items))
        cdf = np.cumsum(affinity / affinity.sum())
        items[mask] = _sample_from_cdf(rng, cdf, m)

    # dedupe (user,item) pairs; implicit strength = interaction count
    key = users.astype(np.int64) * n_items + items
    uniq, inverse = np.unique(key, return_inverse=True)
    implicit_vals = np.bincount(inverse, minlength=len(uniq)).astype(
        np.float32)
    users = (uniq // n_items).astype(np.int32)
    items = (uniq % n_items).astype(np.int32)

    # explicit stars: true-factor dot + noise, 0.5..5 in half-star steps
    scale = 1.0 / math.sqrt(latent_rank)
    Zu = rng.normal(0.0, scale, (n_users, latent_rank)).astype(np.float32)
    Zi = rng.normal(0.0, scale, (n_items, latent_rank)).astype(np.float32)
    dots = np.einsum("nk,nk->n", Zu[users], Zi[items])
    stars = 3.25 + 1.5 * dots + rng.normal(0.0, noise_sigma, len(users))
    explicit_vals = np.clip(np.round(stars * 2.0) / 2.0, 0.5, 5.0).astype(
        np.float32)

    return users, items, implicit_vals, explicit_vals, noise_sigma


def _split(rng: np.random.Generator, n: int, test_fraction: float):
    test_mask = rng.random(n) < test_fraction
    return ~test_mask, test_mask


def _warm_test_mask(users, items, train_mask, test_mask):
    """Mask of test pairs whose user AND item appear in training
    (cold-start rows have zero factors and are not a solver-quality
    signal; the reference's time-split evaluation has the same caveat)."""
    seen_u = np.zeros(users.max() + 1, dtype=bool)
    seen_i = np.zeros(items.max() + 1, dtype=bool)
    seen_u[users[train_mask]] = True
    seen_i[items[train_mask]] = True
    return test_mask & seen_u[users] & seen_i[items]


def run_training_bench(n_users: int = ML20M_USERS,
                       n_items: int = ML20M_ITEMS,
                       n_ratings: int = ML20M_RATINGS,
                       rank: int = 100,
                       iterations: int = 10,
                       explicit_iterations: int = 20,
                       lam: float = 0.1,
                       alpha: float = 1.0,
                       auc_max_users: int = 5_000,
                       test_fraction: float = 0.05,
                       seed: int = 7,
                       run_explicit: bool = True) -> dict:
    """Train implicit (AUC) and explicit (RMSE) ALS at MovieLens scale;
    returns the metrics dict."""
    t0 = time.perf_counter()
    users, items, imp_vals, exp_vals, noise_sigma = synthesize_movielens(
        n_users, n_items, n_ratings, seed=seed)
    synth_s = time.perf_counter() - t0

    rng = np.random.default_rng(seed + 1)
    train_mask, test_mask = _split(rng, len(users), test_fraction)
    user_ids = [str(u) for u in range(n_users)]
    item_ids = [str(i) for i in range(n_items)]

    def timed_train(values, implicit, iters):
        ratings = ParsedRatings(user_ids, item_ids, users[train_mask],
                                items[train_mask], values[train_mask])
        marks = [time.perf_counter()]  # before packing + training
        model = train_als(ratings, rank, lam, alpha, implicit, iters,
                          seed=seed,
                          on_iteration=lambda i, X, Y: marks.append(
                              time.perf_counter()))
        # sweeps[0] pays data packing/upload + XLA compilation;
        # steady-state epoch time = mean of the later sweeps
        sweeps = np.diff(marks)
        return model, sweeps

    # ---- implicit run (the Oryx default mode): held-out per-user AUC
    t0 = time.perf_counter()
    imp_model, sweeps = timed_train(imp_vals, True, iterations)
    imp_total_s = time.perf_counter() - t0
    imp_first_epoch_s = float(sweeps[0])
    imp_epoch_s = float(np.mean(sweeps[1:])) if len(sweeps) > 1 else float(
        sweeps[0])

    warm = _warm_test_mask(users, items, train_mask, test_mask)
    tu, ti = users[warm], items[warm]
    if len(tu) and auc_max_users:
        test_users = np.unique(tu)
        if len(test_users) > auc_max_users:
            chosen = rng.choice(test_users, auc_max_users, replace=False)
            keep = np.isin(tu, chosen)
            tu, ti = tu[keep], ti[keep]
    t0 = time.perf_counter()
    auc = area_under_curve(imp_model.X, imp_model.Y, tu, ti)
    auc_eval_s = time.perf_counter() - t0

    result = {
        "dataset": f"synthetic-ml20m {n_users}x{n_items}, "
                   f"{int(np.count_nonzero(train_mask))} train pairs",
        "rank": rank,
        "synth_s": round(synth_s, 1),
        "implicit_iterations": iterations,
        "implicit_epoch_s": round(imp_epoch_s, 3),
        "implicit_first_epoch_s": round(imp_first_epoch_s, 3),
        "implicit_total_s": round(imp_total_s, 1),
        "implicit_test_auc": round(auc, 4),
        "auc_test_pairs": int(len(tu)),
        "auc_eval_s": round(auc_eval_s, 1),
    }

    # ---- explicit run: held-out RMSE vs the injected noise floor,
    # with the RMSE-vs-iteration CURVE recorded so "the solver is still
    # descending" and "the solver has converged above the floor" are
    # distinguishable claims (Evaluation.java:49-63 semantics)
    if run_explicit:
        ok = warm
        # curve evals run on a SAMPLE between sweeps and their time is
        # excluded from the epoch metric: epoch_s must keep measuring
        # training alone (the north-star metric), comparable across
        # rounds, while the curve proves convergence
        ok_idx = np.nonzero(ok)[0]
        if len(ok_idx) > 200_000:
            ok_idx = rng.choice(ok_idx, 200_000, replace=False)
        cu, ci, cv = users[ok_idx], items[ok_idx], exp_vals[ok_idx]
        curve: list[float] = []
        sweep_times: list[float] = []
        last_exit = [None]

        def on_iter_rmse(i, X, Y):
            entry = time.perf_counter()
            if last_exit[0] is not None:
                sweep_times.append(entry - last_exit[0])
            curve.append(round(rmse(X, Y, cu, ci, cv), 4))
            last_exit[0] = time.perf_counter()

        ratings = ParsedRatings(user_ids, item_ids, users[train_mask],
                                items[train_mask], exp_vals[train_mask])
        t0 = time.perf_counter()
        last_exit[0] = t0
        exp_model = train_als(ratings, rank, lam, alpha, False,
                              explicit_iterations, seed=seed,
                              on_iteration=on_iter_rmse)
        exp_total_s = time.perf_counter() - t0
        # final quality on the FULL warm held-out set
        test_rmse = round(rmse(exp_model.X, exp_model.Y,
                               users[ok], items[ok], exp_vals[ok]), 4)
        # quality gate: converged (plateaued) near the floor — the
        # planted sigma plus half-star quantization and clipping put the
        # achievable floor somewhat above noise_sigma itself
        plateaued = (len(curve) >= 3
                     and abs(curve[-1] - curve[-3]) < 0.005)
        assert test_rmse < 1.5 * noise_sigma and (
            plateaued or test_rmse < 1.1 * noise_sigma), curve
        result.update({
            "explicit_iterations": explicit_iterations,
            "explicit_epoch_s": round(float(np.mean(sweep_times[1:]))
                                      if len(sweep_times) > 1
                                      else sweep_times[0], 3),
            "explicit_first_epoch_s": round(sweep_times[0], 3),
            "explicit_total_s": round(exp_total_s, 1),
            "explicit_test_rmse": test_rmse,
            "explicit_rmse_curve": curve,
            "explicit_noise_floor": noise_sigma,
            "quality_gate": "rmse < 1.5*sigma and plateaued",
        })
    return result


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--users", type=int, default=ML20M_USERS)
    ap.add_argument("--items", type=int, default=ML20M_ITEMS)
    ap.add_argument("--ratings", type=int, default=ML20M_RATINGS)
    ap.add_argument("--rank", type=int, default=100)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--explicit-iterations", type=int, default=20)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-explicit", action="store_true")
    ap.add_argument("--out", help="write full JSON artifact here")
    args = ap.parse_args()

    result = run_training_bench(
        n_users=args.users, n_items=args.items, n_ratings=args.ratings,
        rank=args.rank, iterations=args.iterations,
        explicit_iterations=args.explicit_iterations, seed=args.seed,
        run_explicit=not args.no_explicit)
    import jax
    result["device"] = str(jax.devices()[0].platform)
    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
