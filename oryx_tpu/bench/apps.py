"""k-means and RDF training benchmarks at representative scale.

The reference defers batch-layer performance to "the underlying MLlib
implementations" (docs/docs/performance.html); these record what the
TPU-native trainers sustain so the claim is a number: Lloyd iterations
over millions of points and level-synchronous forest growth over a
covtype-scale table, single chip.

Run: python -m oryx_tpu.bench.apps [--points N] [--examples N]
Prints one JSON line per app.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def bench_kmeans(n_points: int = 5_000_000, dims: int = 20, k: int = 100,
                 iterations: int = 10, seed: int = 5) -> dict:
    from ..app.kmeans.trainer import train_kmeans

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    true_centers = rng.standard_normal((k, dims)).astype(np.float32) * 10
    assign = rng.integers(0, k, n_points)
    # float32 generation directly — a float64 intermediate would double
    # memory and generation time at bench scale
    pts = (true_centers[assign]
           + rng.standard_normal((n_points, dims), dtype=np.float32))

    # one upload, timed separately: training itself is device-resident
    # (only KBs of centers/counts/cost cross the transport), so the
    # timed region measures the Lloyd kernels, not data movement
    t0 = time.perf_counter()
    dev_pts = jnp.asarray(pts)
    dev_pts.block_until_ready()
    upload = time.perf_counter() - t0

    # warm compile with the SAME shapes and static iteration count the
    # timed run uses — jit keys on both, so a smaller warm-up would
    # leave the timed run paying the compile
    train_kmeans(dev_pts, k=k, iterations=iterations, runs=1, seed=seed)
    timings: dict = {}
    t0 = time.perf_counter()
    clusters = train_kmeans(dev_pts, k=k, iterations=iterations, runs=1,
                            seed=seed, timings=timings)
    total = time.perf_counter() - t0
    assert len(clusters) == k
    # quality gate: clustering must capture the planted structure —
    # mean squared distance to the nearest learned center has to be a
    # small fraction of the variance around the global mean (what k=1
    # would score); merged/failed clusterings land near the baseline
    centers = np.stack([c.center for c in clusters]).astype(np.float32)
    d2_total = 0.0
    for s in range(0, n_points, 1_000_000):
        blk = pts[s:s + 1_000_000]
        d = (np.sum(blk * blk, axis=1, keepdims=True)
             - 2.0 * blk @ centers.T
             + np.sum(centers * centers, axis=1)[None, :])
        d2_total += float(np.maximum(d.min(axis=1), 0).sum())
    mean_sq_dist = d2_total / n_points
    baseline_var = float(
        ((pts - pts.mean(axis=0)) ** 2).sum(axis=1).mean())
    assert mean_sq_dist < 0.1 * baseline_var, (mean_sq_dist, baseline_var)
    return {
        "metric": "kmeans_train",
        "points": n_points, "dims": dims, "k": k,
        "iterations": iterations,
        "upload_s": round(upload, 2),
        "total_s": round(total, 4),
        "init_s": round(timings["init_s"], 2),
        "lloyd_s": round(timings["lloyd_s"], 2),
        # per-Lloyd-iteration metrics divide by Lloyd time only, so
        # they stay comparable whatever the initialization strategy
        "iteration_s": round(timings["lloyd_s"] / iterations, 3),
        "points_per_s": round(
            n_points * iterations / timings["lloyd_s"], 0),
        "mean_sq_dist": round(mean_sq_dist, 2),
        "baseline_var": round(baseline_var, 2),
        "quality_gate": "mean_sq_dist < 0.1 * baseline_var",
        # which side of the H2D transfer boundary each number measures
        # (the serving grid labels its tunnel/device split the same
        # way): upload_s is the ONE-TIME host->device copy of the point
        # matrix over this environment's network transport and can
        # dwarf total_s without meaning the training is slow — the
        # timed region is entirely on-chip
        "timing_boundaries": {
            "upload_s": "host->device transfer (one-time, untimed in "
                        "total_s; dominated by the TPU tunnel here)",
            "total_s": "on-chip (warm-compiled train_kmeans call)",
            "init_s": "on-chip (k-means|| initialization)",
            "lloyd_s": "on-chip (Lloyd iterations)",
        },
    }


def bench_rdf(n_examples: int = 1_000_000, n_predictors: int = 20,
              num_trees: int = 20, max_depth: int = 10,
              bins: int = 32, seed: int = 6,
              min_accuracy: float = 0.9) -> dict:
    from ..app.rdf.trainer import train_forest
    from ..app.schema import InputSchema
    from ..common.config import from_dict

    rng = np.random.default_rng(seed)
    x = rng.uniform(-1, 1, (n_examples, n_predictors)).astype(np.float32)
    y = ((x[:, 0] + 0.5 * x[:, 1] - 0.25 * x[:, 2]) > 0).astype(np.int32)
    # held-out split, the reference's eval semantics (Evaluation.java:
    # 27-50 scores the forest on data the trainer never saw)
    n_test = n_examples // 10
    x_train, y_train = x[n_test:], y[n_test:]
    x_test, y_test = x[:n_test], y[:n_test]
    names = [f"f{i}" for i in range(n_predictors)] + ["label"]
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": names,
        "oryx.input-schema.numeric-features": names[:-1],
        "oryx.input-schema.target-feature": "label",
    }))
    t0 = time.perf_counter()
    forest = train_forest(x_train, y_train, schema, category_counts={},
                          num_trees=num_trees, max_depth=max_depth,
                          max_split_candidates=bins, impurity="gini",
                          seed=seed, num_classes=2)
    total = time.perf_counter() - t0
    # second build = the production steady state: the batch layer
    # retrains every generation, and power-of-two level widths make
    # every later build pure compile-cache hits
    timings: dict = {}
    t0 = time.perf_counter()
    train_forest(x_train, y_train, schema, category_counts={},
                 num_trees=num_trees, max_depth=max_depth,
                 max_split_candidates=bins,
                 impurity="gini", seed=seed + 1, num_classes=2,
                 timings=timings)
    warm_total = time.perf_counter() - t0

    # held-out accuracy via the array-form batched forest, on a sample
    # (sample FIRST — materializing the full all-features matrix would
    # do 20x the work for rows never predicted)
    from ..app.rdf.forest_arrays import ForestArrays
    sample = rng.choice(n_test, min(n_test, 50_000), replace=False)
    full = np.full((len(sample), schema.num_features), np.nan, np.float32)
    full[:, :n_predictors] = x_test[sample]
    arrays = ForestArrays(forest, schema.num_features, 2)
    probs = arrays.predict_proba(full)
    acc = float((np.argmax(probs, axis=1) == y_test[sample]).mean())
    assert acc >= min_accuracy, (acc, min_accuracy)  # quality gate
    n_train = n_examples - n_test
    return {
        "metric": "rdf_train",
        "examples": n_train, "predictors": n_predictors,
        "trees": num_trees, "max_depth": max_depth, "bins": bins,
        "total_s": round(total, 4),
        "warm_total_s": round(warm_total, 4),
        "examples_x_trees_per_s": round(n_train * num_trees / total, 0),
        "warm_examples_x_trees_per_s": round(
            n_train * num_trees / warm_total, 0),
        "heldout_accuracy": round(acc, 4),
        "quality_gate": f"heldout_accuracy >= {min_accuracy}",
        # stage decomposition of the warm build (device work is async;
        # each fetch stage absorbs its pending kernel time)
        "warm_decomposition_s": {k: round(v, 2)
                                 for k, v in timings.items()},
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--points", type=int, default=5_000_000)
    ap.add_argument("--examples", type=int, default=1_000_000)
    ap.add_argument("--only", choices=["kmeans", "rdf"], default=None)
    args = ap.parse_args()
    if args.only in (None, "kmeans"):
        print(json.dumps(bench_kmeans(n_points=args.points)))
    if args.only in (None, "rdf"):
        print(json.dumps(bench_rdf(n_examples=args.examples)))


if __name__ == "__main__":
    main()
