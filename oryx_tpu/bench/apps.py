"""k-means and RDF training benchmarks at representative scale.

The reference defers batch-layer performance to "the underlying MLlib
implementations" (docs/docs/performance.html); these record what the
TPU-native trainers sustain so the claim is a number: Lloyd iterations
over millions of points and level-synchronous forest growth over a
covtype-scale table, single chip.

Run: python -m oryx_tpu.bench.apps [--points N] [--examples N]
Prints one JSON line per app.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def bench_kmeans(n_points: int = 5_000_000, dims: int = 20, k: int = 100,
                 iterations: int = 10, seed: int = 5) -> dict:
    from ..app.kmeans.trainer import train_kmeans

    rng = np.random.default_rng(seed)
    true_centers = rng.standard_normal((k, dims)).astype(np.float32) * 10
    assign = rng.integers(0, k, n_points)
    # float32 generation directly — a float64 intermediate would double
    # memory and generation time at bench scale
    pts = (true_centers[assign]
           + rng.standard_normal((n_points, dims), dtype=np.float32))

    # warm compile with the SAME shapes and static iteration count the
    # timed run uses — jit keys on both, so a smaller warm-up would
    # leave the timed run paying the compile
    train_kmeans(pts, k=k, iterations=iterations, runs=1,
                 initialization="random", seed=seed)
    t0 = time.perf_counter()
    clusters = train_kmeans(pts, k=k, iterations=iterations, runs=1,
                            initialization="random", seed=seed)
    total = time.perf_counter() - t0
    assert len(clusters) == k
    return {
        "metric": "kmeans_train",
        "points": n_points, "dims": dims, "k": k,
        "iterations": iterations,
        "total_s": round(total, 2),
        "iteration_s": round(total / iterations, 3),
        "points_per_s": round(n_points * iterations / total, 0),
    }


def bench_rdf(n_examples: int = 1_000_000, n_predictors: int = 20,
              num_trees: int = 20, max_depth: int = 10,
              bins: int = 32, seed: int = 6) -> dict:
    from ..app.rdf.trainer import train_forest
    from ..app.schema import InputSchema
    from ..common.config import from_dict

    rng = np.random.default_rng(seed)
    x = rng.uniform(-1, 1, (n_examples, n_predictors)).astype(np.float32)
    y = ((x[:, 0] + 0.5 * x[:, 1] - 0.25 * x[:, 2]) > 0).astype(np.int32)
    names = [f"f{i}" for i in range(n_predictors)] + ["label"]
    schema = InputSchema(from_dict({
        "oryx.input-schema.feature-names": names,
        "oryx.input-schema.numeric-features": names[:-1],
        "oryx.input-schema.target-feature": "label",
    }))
    t0 = time.perf_counter()
    forest = train_forest(x, y, schema, category_counts={},
                          num_trees=num_trees, max_depth=max_depth,
                          max_split_candidates=bins, impurity="gini",
                          seed=seed, num_classes=2)
    total = time.perf_counter() - t0
    # second build = the production steady state: the batch layer
    # retrains every generation, and power-of-two level widths make
    # every later build pure compile-cache hits
    t0 = time.perf_counter()
    train_forest(x, y, schema, category_counts={}, num_trees=num_trees,
                 max_depth=max_depth, max_split_candidates=bins,
                 impurity="gini", seed=seed + 1, num_classes=2)
    warm_total = time.perf_counter() - t0

    # in-sample accuracy via the array-form batched forest, on a sample
    # (sample FIRST — materializing the full all-features matrix would
    # do 20x the work for rows never predicted)
    from ..app.rdf.forest_arrays import ForestArrays
    sample = rng.choice(n_examples, min(n_examples, 50_000), replace=False)
    full = np.full((len(sample), schema.num_features), np.nan, np.float32)
    full[:, :n_predictors] = x[sample]
    arrays = ForestArrays(forest, schema.num_features, 2)
    probs = arrays.predict_proba(full)
    acc = float((np.argmax(probs, axis=1) == y[sample]).mean())
    return {
        "metric": "rdf_train",
        "examples": n_examples, "predictors": n_predictors,
        "trees": num_trees, "max_depth": max_depth, "bins": bins,
        "total_s": round(total, 2),
        "warm_total_s": round(warm_total, 2),
        "examples_x_trees_per_s": round(n_examples * num_trees / total, 0),
        "warm_examples_x_trees_per_s": round(
            n_examples * num_trees / warm_total, 0),
        "train_accuracy": round(acc, 4),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--points", type=int, default=5_000_000)
    ap.add_argument("--examples", type=int, default=1_000_000)
    ap.add_argument("--only", choices=["kmeans", "rdf"], default=None)
    args = ap.parse_args()
    if args.only in (None, "kmeans"):
        print(json.dumps(bench_kmeans(n_points=args.points)))
    if args.only in (None, "rdf"):
        print(json.dumps(bench_rdf(n_examples=args.examples)))


if __name__ == "__main__":
    main()
