"""Device mesh helpers.

The reference scales out through Spark executors on YARN
(framework/oryx-lambda/.../AbstractSparkLayer.java:137-168 builds the
streaming context whose tasks fan out over the cluster).  The TPU-native
analog is a jax.sharding.Mesh over the chips of a slice: data-parallel
rows of the factor matrices ride the "d" axis, and cross-device
communication is XLA collectives over ICI instead of Spark shuffle.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "local_mesh", "initialize_multihost"]


def initialize_multihost(config=None) -> bool:
    """Join this process to a multi-host JAX cluster, if configured.

    The reference scales across machines with Spark executors over YARN
    plus NCCL-free shuffle; the TPU-native equivalent is
    ``jax.distributed`` — after initialization ``jax.devices()`` spans
    every host's chips (ICI within a slice, DCN across slices), and the
    SAME 1-D mesh + shard_map training code runs unchanged at multi-host
    scale because it only ever names mesh axes, never hosts.

    Config keys (all optional — on Cloud TPU the runtime supplies them
    and a bare ``jax.distributed.initialize()`` suffices):
      oryx.distributed.coordinator-address   host:port of process 0
      oryx.distributed.num-processes
      oryx.distributed.process-id

    Returns True when distributed mode was initialized.  Safe to call
    when unconfigured (no-op) or already initialized.
    """
    coord = num = pid = None
    if config is not None:
        coord = config.get_optional_string(
            "oryx.distributed.coordinator-address")
        if config.has_path("oryx.distributed.num-processes"):
            num = config.get_int("oryx.distributed.num-processes")
        if config.has_path("oryx.distributed.process-id"):
            pid = config.get_int("oryx.distributed.process-id")
    if coord is None and num is None and pid is None:
        return False
    # already joined — idempotent (the introspection surface moved
    # across JAX versions: is_initialized() on newer, global_state
    # earlier; tolerate both)
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return True
    state = getattr(jax.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return True
    # a genuine join failure (unreachable coordinator, bad params) must
    # propagate: silently training single-host when multi-host was
    # configured would be the worst failure mode
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=pid)
    return True


def build_mesh(n_devices: int | None = None, axis: str = "d") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices (all, if
    None).  One axis is the right shape for ALS: both factor matrices are
    row-sharded over it and the opposite factor is all-gathered per
    half-sweep, so a single axis carries all collective traffic."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def local_mesh(axis: str = "d") -> Mesh:
    """Mesh over every device JAX can see (single-host: all local chips)."""
    return build_mesh(None, axis)


def mesh_from_config(config, axis: str = "d") -> Mesh | None:
    """The batch layer's training mesh, or None for single-device.

    ``oryx.batch.streaming.num-executors x executor-cores`` is the
    requested total device count (the reference's executor sizing,
    reference.conf:146-150 / oryx-run.sh:160-231, re-read as chips);
    the mesh shrinks to the devices actually present.
    """
    master = config.get_string("oryx.batch.streaming.master")
    if master == "cpu":
        return None
    # multi-host: join the cluster BEFORE the first jax.devices() call
    # so the mesh spans every host's chips
    initialize_multihost(config)
    if jax.default_backend() == "cpu" and master != "mesh":
        # "auto" on a CPU backend: virtual host devices exist only for
        # sharding tests; single-device XLA is faster for real work.
        # master = "mesh" forces a mesh over them (tests, dry runs).
        return None
    if jax.process_count() > 1:
        # multi-host: every process's local devices MUST be in the mesh
        # (a truncated mesh would exclude some hosts' chips and deadlock
        # their shard_map dispatches at the first collective), so the
        # executor sizing is advisory only here
        return build_mesh(None, axis)
    requested = (config.get_int("oryx.batch.streaming.num-executors")
                 * config.get_int("oryx.batch.streaming.executor-cores"))
    n = min(requested, len(jax.devices()))
    if n <= 1:
        return None
    return build_mesh(n, axis)
