"""Device mesh helpers.

The reference scales out through Spark executors on YARN
(framework/oryx-lambda/.../AbstractSparkLayer.java:137-168 builds the
streaming context whose tasks fan out over the cluster).  The TPU-native
analog is a jax.sharding.Mesh over the chips of a slice: data-parallel
rows of the factor matrices ride the "d" axis, and cross-device
communication is XLA collectives over ICI instead of Spark shuffle.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "local_mesh"]


def build_mesh(n_devices: int | None = None, axis: str = "d") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices (all, if
    None).  One axis is the right shape for ALS: both factor matrices are
    row-sharded over it and the opposite factor is all-gathered per
    half-sweep, so a single axis carries all collective traffic."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def local_mesh(axis: str = "d") -> Mesh:
    """Mesh over every device JAX can see (single-host: all local chips)."""
    return build_mesh(None, axis)
