"""Device mesh helpers.

The reference scales out through Spark executors on YARN
(framework/oryx-lambda/.../AbstractSparkLayer.java:137-168 builds the
streaming context whose tasks fan out over the cluster).  The TPU-native
analog is a jax.sharding.Mesh over the chips of a slice: data-parallel
rows of the factor matrices ride the "d" axis, and cross-device
communication is XLA collectives over ICI instead of Spark shuffle.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "local_mesh"]


def build_mesh(n_devices: int | None = None, axis: str = "d") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices (all, if
    None).  One axis is the right shape for ALS: both factor matrices are
    row-sharded over it and the opposite factor is all-gathered per
    half-sweep, so a single axis carries all collective traffic."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def local_mesh(axis: str = "d") -> Mesh:
    """Mesh over every device JAX can see (single-host: all local chips)."""
    return build_mesh(None, axis)


def mesh_from_config(config, axis: str = "d") -> Mesh | None:
    """The batch layer's training mesh, or None for single-device.

    ``oryx.batch.streaming.num-executors x executor-cores`` is the
    requested total device count (the reference's executor sizing,
    reference.conf:146-150 / oryx-run.sh:160-231, re-read as chips);
    the mesh shrinks to the devices actually present.
    """
    master = config.get_string("oryx.batch.streaming.master")
    if master == "cpu":
        return None
    if jax.default_backend() == "cpu" and master != "mesh":
        # "auto" on a CPU backend: virtual host devices exist only for
        # sharding tests; single-device XLA is faster for real work.
        # master = "mesh" forces a mesh over them (tests, dry runs).
        return None
    requested = (config.get_int("oryx.batch.streaming.num-executors")
                 * config.get_int("oryx.batch.streaming.executor-cores"))
    n = min(requested, len(jax.devices()))
    if n <= 1:
        return None
    return build_mesh(n, axis)
