"""Multi-device parallelism: mesh construction and the distributed ALS
trainer (shard_map over ICI with XLA collectives).

This is the TPU-native replacement for the reference's cluster-scale
training path (Spark MLlib ALS block partitioning,
app/oryx-app-mllib/.../als/ALSUpdate.java:141-152) and its Spark
driver/executor communication backend (SURVEY §5.8): shuffles become
all_gather/psum over the device mesh.
"""

from .mesh import build_mesh, local_mesh
from .als_dist import (
    BlockedRatings,
    block_ratings,
    block_ratings_ring,
    make_train_step,
    train_als_distributed,
)

__all__ = [
    "build_mesh",
    "local_mesh",
    "BlockedRatings",
    "block_ratings",
    "block_ratings_ring",
    "make_train_step",
    "train_als_distributed",
]
