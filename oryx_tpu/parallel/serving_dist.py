"""Sharded serving scan: the item matrix row-sharded over a device
mesh, per-shard top-k, on-device merge.

Reference: the serving model partitions its item matrix into hash
partitions scanned by a thread pool with a streaming top-N merge
(PartitionedFeatureVectors.java:84-148, ALSServingModel.java:265-280).
The TPU-native analog scales the same way across CHIPS: rows of Y live
sharded over a 1-D mesh, every query's partial top-k is computed on the
shard that owns the rows, partials ride one all_gather over ICI, and
the merge happens on device — one jitted SPMD program, no host fan-in.

This is the capacity story past a single chip's HBM: a 40M x 250 bf16
item matrix (20 GB) serves from 2 chips, 160M items from 8.  The
single-chip serving model (app/als/serving_model.py) remains the
production path up to ~20M items; this scorer is the P4/P5 scale-out
the driver dry-runs on a virtual mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
try:  # moved out of experimental in JAX 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..app.als.feature_vectors import resolve_dtype
from ..app.als.serving_model import _pad_k, _q_cast

__all__ = ["ShardedItemScorer"]


def _shardmap_norepcheck_kwargs() -> dict:
    """The all_gather-merged outputs ARE replicated, but shard_map's
    static replication checker cannot infer that; the disabling kwarg
    was renamed across JAX versions (check_rep -> check_vma)."""
    import inspect
    params = inspect.signature(shard_map).parameters
    for name in ("check_vma", "check_rep"):
        if name in params:
            return {name: False}
    return {}


def _make_kernel(mesh: Mesh, k_shard: int, k_final: int, axis: str):
    """``k_shard`` candidates leave each shard; ``k_final`` survive the
    merge.  They are independent: a shard can never contribute more
    than its own row count, but the MERGED result may be wider than any
    one shard's candidate list (how_many > rows-per-shard)."""
    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis, None), P(axis), P(None, None)),
             out_specs=(P(None, None), P(None, None)),
             **_shardmap_norepcheck_kwargs())
    def scorer(Y_local, active_local, Q):
        n_local = Y_local.shape[0]
        # bf16 stores: keep the scan on the native bf16 MXU path
        # (serving_model._q_cast rationale)
        scores = jnp.matmul(_q_cast(Q, Y_local), Y_local.T,
                            preferred_element_type=jnp.float32)
        scores = jnp.where(active_local[None, :], scores, -jnp.inf)
        ls, li = jax.lax.top_k(scores, k_shard)        # (B, ks) local
        gi = li + jax.lax.axis_index(axis) * n_local   # global row ids
        # partials from every shard: (n_dev, B, ks) -> (B, n_dev*ks)
        gs = jax.lax.all_gather(ls, axis)
        gidx = jax.lax.all_gather(gi, axis)
        b = Q.shape[0]
        gs = jnp.moveaxis(gs, 0, 1).reshape(b, -1)
        gidx = jnp.moveaxis(gidx, 0, 1).reshape(b, -1)
        ms, sel = jax.lax.top_k(gs, k_final)
        mi = jnp.take_along_axis(gidx, sel, axis=1)
        return ms, mi

    return jax.jit(scorer)


class ShardKernelCache:
    """Per-(k_shard, k_final) compiled SPMD merge kernels for one mesh —
    the shard plan shared by :class:`ShardedItemScorer` and the serving
    model's configured sharded mode (``oryx.serving.api.item-shards``)."""

    def __init__(self, mesh: Mesh, axis: str = "d"):
        self.mesh = mesh
        self.axis = axis
        self._kernels: dict[tuple[int, int], object] = {}

    def top_k(self, Y, active, Q_dev, k: int):
        """(scores, global_row_idx) of the merged per-shard top-k for a
        replicated query batch; ``k`` is clamped to the global row
        count and each shard's contribution to its local rows."""
        n_rows = int(Y.shape[0])
        n_local = n_rows // self.mesh.devices.size
        k_shard = min(k, n_local)
        k_final = min(k, k_shard * self.mesh.devices.size)
        kern = self._kernels.get((k_shard, k_final))
        if kern is None:
            kern = self._kernels[(k_shard, k_final)] = _make_kernel(
                self.mesh, k_shard, k_final, self.axis)
        return kern(Y, active, Q_dev)

    def replicate(self, Q: np.ndarray):
        return jax.device_put(
            Q, NamedSharding(self.mesh, P(None, None)))


class ShardedItemScorer:
    """Row-sharded item matrix + batched exact top-N over a mesh.

    Built from an id list and factor matrix (e.g. a MODEL publish);
    rows pad to a multiple of the mesh size with inactive entries, so
    every shard is identical in shape and the whole scan is one SPMD
    dispatch."""

    def __init__(self, mesh: Mesh, ids: Sequence[str], Y: np.ndarray,
                 dtype="bfloat16", axis: str = "d"):
        if len(ids) != len(Y):
            raise ValueError("one id per row required")
        self.mesh = mesh
        self.axis = axis
        self._ids = list(ids)
        n_dev = mesh.devices.size
        n = len(self._ids)
        n_pad = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
        dt = resolve_dtype(dtype)
        padded = np.zeros((n_pad, Y.shape[1]), dtype=dt)
        padded[:n] = np.asarray(Y).astype(dt)
        active = np.zeros(n_pad, dtype=bool)
        active[:n] = True
        row = NamedSharding(mesh, P(axis))
        self._Y = jax.device_put(padded, row)
        self._active = jax.device_put(active, row)
        self.features = int(Y.shape[1])
        self._kernels = ShardKernelCache(mesh, axis)

    def __len__(self) -> int:
        return len(self._ids)

    def memory_bytes_per_device(self) -> int:
        return (self._Y.nbytes + self._active.nbytes) \
            // self.mesh.devices.size

    def top_n_batch(self, how_many: int,
                    queries: np.ndarray) -> list[list[tuple[str, float]]]:
        Q = np.asarray(queries, dtype=np.float32)
        if Q.ndim != 2 or Q.shape[1] != self.features:
            raise ValueError("queries must be (B, features)")
        n_req = Q.shape[0]
        if n_req == 0:
            return []
        b_pad = _pad_k(n_req)
        if b_pad != n_req:
            Q = np.concatenate(
                [Q, np.zeros((b_pad - n_req, Q.shape[1]), np.float32)])
        scores, idx = jax.device_get(self._kernels.top_k(
            self._Y, self._active, self._kernels.replicate(Q),
            min(_pad_k(how_many), int(self._Y.shape[0]))))
        out: list[list[tuple[str, float]]] = []
        for b in range(n_req):
            row: list[tuple[str, float]] = []
            for s, i in zip(scores[b].tolist(), idx[b].tolist()):
                if s == float("-inf") or len(row) == how_many:
                    break
                row.append((self._ids[i], s))
            out.append(row)
        return out
