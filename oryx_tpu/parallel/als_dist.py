"""Distributed ALS: one jitted training step over a device mesh.

Reference counterpart: Spark MLlib's block-partitioned ALS, invoked at
app/oryx-app-mllib/.../als/ALSUpdate.java:141-152, where users x items
blocks are shuffled between executors each half-sweep.

TPU-native redesign (NOT a block-shuffle translation):
 - both factor matrices are ROW-SHARDED over the mesh axis "d"
   (X: users/d, Y: items/d) and live in HBM;
 - interactions are pre-blocked on host into a dense padded per-row
   layout (cols/vals/mask of shape (rows, P)), row-sharded the same way,
   so every device solves the normal equations for its own row block
   with ONE batched MXU matmul — static shapes, no per-row loop;
 - per half-sweep the opposite factor is all-gathered over ICI
   (lax.all_gather) and its Gramian is formed by psum of local partial
   Gramians (lax.psum) — these two collectives replace the Spark
   shuffle entirely;
 - the whole two-half-sweep step is a single shard_map-ed jitted
   program; run it `iterations` times.

This scales the memory of the blocked interaction layout and the solve
FLOPs linearly with devices; the all-gathered opposite factor is the
same replicate-the-smaller-side tradeoff MLlib makes with its block
broadcast.
"""

from __future__ import annotations

import math
import zlib
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
try:  # moved out of experimental in JAX 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..app.als.common import ParsedRatings
from ..app.als.trainer import ALSModel, _solve_batch
from ..common.rand import RandomManager

__all__ = ["BlockedRatings", "block_ratings", "make_train_step",
           "train_als_distributed"]


class BlockedRatings(NamedTuple):
    """Dense padded per-row interaction blocks for both half-sweeps.

    Row counts are padded to a multiple of the mesh size; padding rows
    have all-zero masks and solve to zero-ish vectors that are sliced
    away at the end.
    """

    n_users: int          # true (unpadded) user count
    n_items: int          # true (unpadded) item count
    u_cols: np.ndarray    # (n_users_pad, Pu) int32 item index per slot
    u_vals: np.ndarray    # (n_users_pad, Pu) float32
    u_mask: np.ndarray    # (n_users_pad, Pu) float32 1.0 at real entries
    i_cols: np.ndarray    # (n_items_pad, Pi) int32 user index per slot
    i_vals: np.ndarray    # (n_items_pad, Pi) float32
    i_mask: np.ndarray    # (n_items_pad, Pi) float32


def _pad_rows(n: int, n_dev: int) -> int:
    return max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)


def _dense_block(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 n_rows_pad: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    order = np.argsort(rows, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.bincount(rows, minlength=n_rows_pad)
    p = 1 << max(0, int(counts.max(initial=1) - 1).bit_length())
    bcols = np.zeros((n_rows_pad, p), dtype=np.int32)
    bvals = np.zeros((n_rows_pad, p), dtype=np.float32)
    bmask = np.zeros((n_rows_pad, p), dtype=np.float32)
    slot = np.concatenate([np.arange(c) for c in counts if c > 0]) \
        if len(rows) else np.zeros(0, np.int64)
    bcols[rows, slot] = cols
    bvals[rows, slot] = vals
    bmask[rows, slot] = 1.0
    return bcols, bvals, bmask


def block_ratings(ratings: ParsedRatings, n_devices: int) -> BlockedRatings:
    """Build the device-blocked layout from aggregated COO interactions."""
    n_users = len(ratings.user_ids)
    n_items = len(ratings.item_ids)
    nu_pad = _pad_rows(n_users, n_devices)
    ni_pad = _pad_rows(n_items, n_devices)
    u_cols, u_vals, u_mask = _dense_block(
        ratings.users, ratings.items, ratings.values, nu_pad)
    i_cols, i_vals, i_mask = _dense_block(
        ratings.items, ratings.users, ratings.values, ni_pad)
    return BlockedRatings(n_users, n_items,
                          u_cols, u_vals, u_mask, i_cols, i_vals, i_mask)


def make_train_step(mesh: Mesh, lam: float, alpha: float, implicit: bool,
                    axis: str = "d"):
    """Build the jitted distributed step: (X, Y, blocks…) -> (X', Y').

    All array arguments are expected sharded with PartitionSpec((axis,))
    on their leading (row) dimension.
    """

    def _half(opposite_local, cols, vals, mask):
        # collectives: gather the opposite factor over ICI; Gramian by
        # psum of local partials (only needed for the implicit base term
        # but cheap either way, and it keeps one code path)
        full = jax.lax.all_gather(opposite_local, axis, axis=0, tiled=True)
        g_local = jnp.matmul(opposite_local.T, opposite_local,
                             preferred_element_type=jnp.float32)
        G = jax.lax.psum(g_local, axis)
        Yg = full[cols]  # (rows_local, P, k)
        x = _solve_batch(Yg, vals, mask, G,
                         jnp.float32(lam), jnp.float32(alpha), implicit)
        # padding rows (no interactions) can produce a singular system;
        # pin them to zero so they never poison the next Gramian/gather
        n = jnp.sum(mask, axis=1)
        return jnp.where((n > 0.0)[:, None], x, 0.0)

    def _step(X, Y, u_cols, u_vals, u_mask, i_cols, i_vals, i_mask):
        X = _half(Y, u_cols, u_vals, u_mask)
        Y = _half(X, i_cols, i_vals, i_mask)
        return X, Y

    spec = P(axis)
    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec, spec))
    return jax.jit(sharded)


def train_als_distributed(ratings: ParsedRatings, features: int, lam: float,
                          alpha: float, implicit: bool, iterations: int,
                          mesh: Mesh, seed: int | None = None,
                          axis: str = "d") -> ALSModel:
    """Full multi-device ALS training loop; returns host-side factors."""
    n_dev = mesh.devices.size
    k = features
    if len(ratings.user_ids) == 0 or len(ratings.item_ids) == 0:
        return ALSModel(ratings.user_ids, ratings.item_ids,
                        np.zeros((0, k), np.float32),
                        np.zeros((0, k), np.float32))
    blocks = block_ratings(ratings, n_dev)

    if seed is None:
        if jax.process_count() > 1:
            # multi-controller SPMD: device_put of the init requires
            # the SAME host value on every process, and per-process RNG
            # state differs — derive the seed from the (identical by
            # contract) input instead
            seed = zlib.crc32(np.ascontiguousarray(
                ratings.values).tobytes()) & 0x7FFFFFFF
        else:
            seed = RandomManager.random_seed()
    rng = np.random.default_rng(seed)
    Y0 = (rng.standard_normal((blocks.i_cols.shape[0], k))
          / math.sqrt(k)).astype(np.float32)
    Y0[blocks.n_items:] = 0.0  # padding rows must not leak into the Gramian
    X0 = np.zeros((blocks.u_cols.shape[0], k), dtype=np.float32)

    row_sharding = NamedSharding(mesh, P(axis))
    put = partial(jax.device_put, device=row_sharding)
    X, Y = put(X0), put(Y0)
    args = tuple(put(a) for a in (blocks.u_cols, blocks.u_vals, blocks.u_mask,
                                  blocks.i_cols, blocks.i_vals, blocks.i_mask))
    step = make_train_step(mesh, lam, alpha, implicit, axis)
    for _ in range(iterations):
        X, Y = step(X, Y, *args)
    if jax.process_count() > 1:
        # multi-host: a row-sharded factor is not fully addressable
        # from any one process; replicate (one all-gather each) so
        # every process fetches the complete model for PMML publish —
        # the analog of the reference collecting factors to the driver
        # (ALSUpdate.mfModelToPMML :430-473)
        rep = jax.jit(lambda a: a,
                      out_shardings=NamedSharding(mesh, P()))
        X, Y = rep(X), rep(Y)
    Xh = np.asarray(X)[:blocks.n_users]
    Yh = np.asarray(Y)[:blocks.n_items]
    return ALSModel(ratings.user_ids, ratings.item_ids, Xh, Yh)
