"""Distributed ALS: one jitted training step over a device mesh.

Reference counterpart: Spark MLlib's block-partitioned ALS, invoked at
app/oryx-app-mllib/.../als/ALSUpdate.java:141-152, where users x items
blocks are shuffled between executors each half-sweep.

TPU-native redesign (NOT a block-shuffle translation):
 - both factor matrices are ROW-SHARDED over the mesh axis "d"
   (X: users/d, Y: items/d) and live in HBM;
 - interactions are pre-blocked on host into a dense padded per-row
   layout (cols/vals/mask of shape (rows, P)), row-sharded the same way,
   so every device solves the normal equations for its own row block
   with ONE batched MXU matmul — static shapes, no per-row loop;
 - per half-sweep the opposite factor is all-gathered over ICI
   (lax.all_gather) and its Gramian is formed by psum of local partial
   Gramians (lax.psum) — these two collectives replace the Spark
   shuffle entirely;
 - the whole two-half-sweep step is a single shard_map-ed jitted
   program; run it `iterations` times.

This scales the memory of the blocked interaction layout and the solve
FLOPs linearly with devices; the all-gathered opposite factor is the
same replicate-the-smaller-side tradeoff MLlib makes with its block
broadcast.

Multi-host path (``mode="ring"``, the default when the mesh spans
processes): the all-gather + serialized psum become a **ring
half-sweep** — the opposite factor's row blocks rotate around the mesh
axis via ``lax.ppermute`` while each device accumulates the partial
normal equations for the interactions whose columns live in the
resident block (the interactions are pre-split per (row, owner-block)
on host, so total einsum slots stay ~P — no n_dev× FLOP blow-up).  The
Gramian accumulates per hop from the resident block, so the "psum" is
interleaved with — not serialized after — the per-row solve build, and
the full opposite factor is NEVER materialized on any device: peak
memory per half-sweep is one rotating block (rows/n_dev × k) instead
of the whole matrix.  Over DCN (multi-host) this is the difference
between overlapping each hop's transfer with a block's worth of MXU
work and stalling the whole step behind one all-gather.  Factor
buffers are donated to the jitted step (X/Y updated in place across
iterations) on backends that support donation.
"""

from __future__ import annotations

import math
import zlib
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
try:  # moved out of experimental in JAX 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..app.als.common import ParsedRatings
from ..app.als.trainer import ALSModel, _solve_batch
from ..common.rand import RandomManager

__all__ = ["BlockedRatings", "block_ratings", "block_ratings_ring",
           "make_train_step", "train_als_distributed"]


class BlockedRatings(NamedTuple):
    """Dense padded per-row interaction blocks for both half-sweeps.

    Row counts are padded to a multiple of the mesh size; padding rows
    have all-zero masks and solve to zero-ish vectors that are sliced
    away at the end.
    """

    n_users: int          # true (unpadded) user count
    n_items: int          # true (unpadded) item count
    u_cols: np.ndarray    # (n_users_pad, Pu) int32 item index per slot
    u_vals: np.ndarray    # (n_users_pad, Pu) float32
    u_mask: np.ndarray    # (n_users_pad, Pu) float32 1.0 at real entries
    i_cols: np.ndarray    # (n_items_pad, Pi) int32 user index per slot
    i_vals: np.ndarray    # (n_items_pad, Pi) float32
    i_mask: np.ndarray    # (n_items_pad, Pi) float32


def _pad_rows(n: int, n_dev: int) -> int:
    return max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _dense_block(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 n_rows_pad: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    order = np.argsort(rows, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.bincount(rows, minlength=n_rows_pad)
    p = 1 << max(0, int(counts.max(initial=1) - 1).bit_length())
    bcols = np.zeros((n_rows_pad, p), dtype=np.int32)
    bvals = np.zeros((n_rows_pad, p), dtype=np.float32)
    bmask = np.zeros((n_rows_pad, p), dtype=np.float32)
    slot = np.concatenate([np.arange(c) for c in counts if c > 0]) \
        if len(rows) else np.zeros(0, np.int64)
    bcols[rows, slot] = cols
    bvals[rows, slot] = vals
    bmask[rows, slot] = 1.0
    return bcols, bvals, bmask


def block_ratings(ratings: ParsedRatings, n_devices: int) -> BlockedRatings:
    """Build the device-blocked layout from aggregated COO interactions."""
    n_users = len(ratings.user_ids)
    n_items = len(ratings.item_ids)
    nu_pad = _pad_rows(n_users, n_devices)
    ni_pad = _pad_rows(n_items, n_devices)
    u_cols, u_vals, u_mask = _dense_block(
        ratings.users, ratings.items, ratings.values, nu_pad)
    i_cols, i_vals, i_mask = _dense_block(
        ratings.items, ratings.users, ratings.values, ni_pad)
    return BlockedRatings(n_users, n_items,
                          u_cols, u_vals, u_mask, i_cols, i_vals, i_mask)


def _owner_block(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 n_rows_pad: int, block_rows: int, n_dev: int
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-(row, owner-block) padded layout for the ring half-sweep:
    slot (r, b, :) holds row r's interactions whose opposite index
    lives in block b, as LOCAL indices within the block.  Total real
    slots equal the dense layout's — the ring schedule then touches
    each interaction exactly once (at the hop its block is resident),
    so the per-row-solve FLOPs match the all-gather path instead of
    multiplying by n_dev."""
    owner = cols // block_rows
    key = rows.astype(np.int64) * n_dev + owner
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    counts = np.bincount(key_s, minlength=n_rows_pad * n_dev)
    p = _next_pow2(max(1, int(counts.max(initial=1))))
    # within-group slot index, vectorized (groups are contiguous in
    # key order)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot = np.arange(len(key_s), dtype=np.int64) - starts[key_s]
    bcols = np.zeros((n_rows_pad * n_dev, p), dtype=np.int32)
    bvals = np.zeros((n_rows_pad * n_dev, p), dtype=np.float32)
    bmask = np.zeros((n_rows_pad * n_dev, p), dtype=np.float32)
    bcols[key_s, slot] = (cols[order] - owner[order] * block_rows
                          ).astype(np.int32)
    bvals[key_s, slot] = vals[order]
    bmask[key_s, slot] = 1.0
    shape = (n_rows_pad, n_dev, p)
    return bcols.reshape(shape), bvals.reshape(shape), bmask.reshape(shape)


def block_ratings_ring(ratings: ParsedRatings,
                       n_devices: int) -> BlockedRatings:
    """The ring half-sweep's layout: same six arrays as
    :func:`block_ratings` but shaped ``(rows_pad, n_dev, P_block)`` —
    slab ``[:, b, :]`` is the interactions resolved while block ``b``
    of the opposite factor is resident on this device."""
    n_users = len(ratings.user_ids)
    n_items = len(ratings.item_ids)
    nu_pad = _pad_rows(n_users, n_devices)
    ni_pad = _pad_rows(n_items, n_devices)
    u = _owner_block(ratings.users, ratings.items, ratings.values,
                     nu_pad, ni_pad // n_devices, n_devices)
    i = _owner_block(ratings.items, ratings.users, ratings.values,
                     ni_pad, nu_pad // n_devices, n_devices)
    return BlockedRatings(n_users, n_items, *u, *i)


def make_train_step(mesh: Mesh, lam: float, alpha: float, implicit: bool,
                    axis: str = "d", mode: str = "gather",
                    donate: bool | None = None):
    """Build the jitted distributed step: (X, Y, blocks…) -> (X', Y').

    All array arguments are expected sharded with PartitionSpec((axis,))
    on their leading (row) dimension — blocks from :func:`block_ratings`
    for ``mode="gather"``, :func:`block_ratings_ring` for
    ``mode="ring"`` (the multi-host layout: per-row solves overlapped
    with the Gramian reduction, no materialized full opposite factor).

    ``donate`` donates the X/Y factor buffers to the step so iterations
    update HBM in place; None = donate wherever the backend supports it
    (CPU's donation is a no-op warning, so tests opt in explicitly).
    """
    n_dev = int(mesh.devices.size)

    def _half_gather(opposite_local, cols, vals, mask):
        # collectives: gather the opposite factor over ICI; Gramian by
        # psum of local partials (only needed for the implicit base term
        # but cheap either way, and it keeps one code path)
        full = jax.lax.all_gather(opposite_local, axis, axis=0, tiled=True)
        g_local = jnp.matmul(opposite_local.T, opposite_local,
                             preferred_element_type=jnp.float32)
        G = jax.lax.psum(g_local, axis)
        Yg = full[cols]  # (rows_local, P, k)
        x = _solve_batch(Yg, vals, mask, G,
                         jnp.float32(lam), jnp.float32(alpha), implicit)
        # padding rows (no interactions) can produce a singular system;
        # pin them to zero so they never poison the next Gramian/gather
        n = jnp.sum(mask, axis=1)
        return jnp.where((n > 0.0)[:, None], x, 0.0)

    def _half_ring(opposite_local, cols_b, vals_b, mask_b):
        """One ring half-sweep: the opposite factor's blocks rotate via
        ppermute; each hop folds the resident block's interactions into
        the accumulating normal equations AND the Gramian, so the
        communication of hop t+1 overlaps the einsum of hop t (XLA
        async collectives) instead of the whole solve waiting on an
        all-gather + psum.  Padding slots carry zero mask/vals and
        clamp their gathers to row 0 — they contribute exact zeros,
        the same contract as the dense layout."""
        k = opposite_local.shape[1]
        d = jax.lax.axis_index(axis)
        rows_local = cols_b.shape[0]
        n_u = jnp.sum(mask_b, axis=(1, 2))
        A = jnp.zeros((rows_local, k, k), dtype=jnp.float32)
        b = jnp.zeros((rows_local, k), dtype=jnp.float32)
        G = jnp.zeros((k, k), dtype=jnp.float32)
        alpha32 = jnp.float32(alpha)
        block = opposite_local
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        for t in range(n_dev):
            # device d holds block (d - t) mod n_dev at hop t
            j = jax.lax.rem(d - t + n_dev, n_dev)
            cols = jnp.take(cols_b, j, axis=1)
            vals = jnp.take(vals_b, j, axis=1)
            mask = jnp.take(mask_b, j, axis=1)
            if implicit:
                w = alpha32 * jnp.abs(vals) * mask
                tt = (1.0 + w) * (vals > 0.0)
            else:
                w = mask
                tt = vals * mask
            Yg = block[cols]  # (rows_local, Pb, k)
            A = A + jnp.einsum("bpk,bpl->bkl", Yg * w[:, :, None], Yg,
                               preferred_element_type=jnp.float32)
            b = b + jnp.einsum("bpk,bp->bk", Yg, tt,
                               preferred_element_type=jnp.float32)
            if implicit:
                # the Gramian's block-j term, computed while block j is
                # HERE — the all-reduce dissolves into the ring
                G = G + jnp.matmul(block.T, block,
                                   preferred_element_type=jnp.float32)
            if t < n_dev - 1:
                block = jax.lax.ppermute(block, axis, perm)
        if implicit:
            A = A + G[None, :, :]
        A = A + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * \
            jnp.eye(k, dtype=A.dtype)[None]
        x = jnp.linalg.solve(A, b[..., None])[..., 0]
        return jnp.where((n_u > 0.0)[:, None], x, 0.0)

    half = {"gather": _half_gather, "ring": _half_ring}[mode]

    def _step(X, Y, u_cols, u_vals, u_mask, i_cols, i_vals, i_mask):
        X = half(Y, u_cols, u_vals, u_mask)
        Y = half(X, i_cols, i_vals, i_mask)
        return X, Y

    spec = P(axis)
    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec, spec))
    if donate is None:
        donate = jax.default_backend() not in ("cpu",)
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


def train_als_distributed(ratings: ParsedRatings, features: int, lam: float,
                          alpha: float, implicit: bool, iterations: int,
                          mesh: Mesh, seed: int | None = None,
                          axis: str = "d", mode: str = "auto",
                          donate: bool | None = None) -> ALSModel:
    """Full multi-device ALS training loop; returns host-side factors.

    ``mode``: "gather" (all_gather + psum — the single-host default),
    "ring" (ppermute ring with the Gramian reduction overlapped into
    the per-row-solve build — the multi-host path), or "auto" = ring
    exactly when the mesh spans processes (DCN hops are where the
    overlap pays; within one host's ICI the all-gather is cheap)."""
    n_dev = mesh.devices.size
    k = features
    if mode == "auto":
        mode = "ring" if jax.process_count() > 1 else "gather"
    if len(ratings.user_ids) == 0 or len(ratings.item_ids) == 0:
        return ALSModel(ratings.user_ids, ratings.item_ids,
                        np.zeros((0, k), np.float32),
                        np.zeros((0, k), np.float32))
    blocks = (block_ratings_ring(ratings, n_dev) if mode == "ring"
              else block_ratings(ratings, n_dev))

    if seed is None:
        if jax.process_count() > 1:
            # multi-controller SPMD: device_put of the init requires
            # the SAME host value on every process, and per-process RNG
            # state differs — derive the seed from the (identical by
            # contract) input instead
            seed = zlib.crc32(np.ascontiguousarray(
                ratings.values).tobytes()) & 0x7FFFFFFF
        else:
            seed = RandomManager.random_seed()
    rng = np.random.default_rng(seed)
    Y0 = (rng.standard_normal((blocks.i_cols.shape[0], k))
          / math.sqrt(k)).astype(np.float32)
    Y0[blocks.n_items:] = 0.0  # padding rows must not leak into the Gramian
    X0 = np.zeros((blocks.u_cols.shape[0], k), dtype=np.float32)

    row_sharding = NamedSharding(mesh, P(axis))
    put = partial(jax.device_put, device=row_sharding)
    X, Y = put(X0), put(Y0)
    args = tuple(put(a) for a in (blocks.u_cols, blocks.u_vals, blocks.u_mask,
                                  blocks.i_cols, blocks.i_vals, blocks.i_mask))
    step = make_train_step(mesh, lam, alpha, implicit, axis, mode=mode,
                           donate=donate)
    for _ in range(iterations):
        X, Y = step(X, Y, *args)
    if jax.process_count() > 1:
        # multi-host: a row-sharded factor is not fully addressable
        # from any one process; replicate (one all-gather each) so
        # every process fetches the complete model for PMML publish —
        # the analog of the reference collecting factors to the driver
        # (ALSUpdate.mfModelToPMML :430-473)
        rep = jax.jit(lambda a: a,
                      out_shardings=NamedSharding(mesh, P()))
        X, Y = rep(X), rep(Y)
    Xh = np.asarray(X)[:blocks.n_users]
    Yh = np.asarray(Y)[:blocks.n_items]
    return ALSModel(ratings.user_ids, ratings.item_ids, Xh, Yh)
