"""Distributed k-means: Lloyd iterations over a device mesh.

Reference counterpart: Spark MLlib KMeans.train invoked at
app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:107-120, where each
iteration is a map (assign) + reduceByKey (per-cluster sums) shuffle
over executors.

TPU-native redesign: points are ROW-SHARDED over the mesh axis and
never move; centers are replicated.  Each Lloyd iteration is, per
device, one (n_local, k) distance matmul + one one-hot reduction
matmul (both MXU work), followed by a single psum of the (k, d) sums /
(k,) counts over ICI — the collective that replaces the shuffle.  The
whole iteration loop is a lax.scan inside one shard_map-ed jit, so a
full training run is a single device program.

Initialization (k-means|| / random) runs on host exactly like the
single-device trainer — it is a few tiny passes — and the resulting
centers are broadcast.
"""

from __future__ import annotations

import logging
import math
from functools import partial

import jax
import jax.numpy as jnp
try:  # moved out of experimental in JAX 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..app.kmeans.common import ClusterInfo, assign_points
from ..app.kmeans.trainer import (K_MEANS_PARALLEL, RANDOM, _init_parallel)
from ..common.rand import RandomManager

_log = logging.getLogger(__name__)

__all__ = ["make_lloyd_step", "train_kmeans_distributed"]


def make_lloyd_step(mesh: Mesh, k: int, iterations: int, axis: str = "d"):
    """Build the jitted distributed Lloyd program:
    (points_local, weights_local, centers0) -> (centers, cost).

    ``points``/``weights`` sharded on rows; centers replicated.
    Padding rows carry weight 0 and never influence sums or cost.
    """

    def _run(points, w, centers0):
        pp = jnp.sum(points * points, axis=1)

        def step(centers, _):
            d = (pp[:, None]
                 - 2.0 * jnp.matmul(points, centers.T,
                                    preferred_element_type=jnp.float32)
                 + jnp.sum(centers * centers, axis=1)[None, :])
            idx = jnp.argmin(d, axis=1)
            onehot = jax.nn.one_hot(idx, k, dtype=points.dtype) * w[:, None]
            counts = jax.lax.psum(jnp.sum(onehot, axis=0), axis)
            sums = jax.lax.psum(
                jnp.matmul(onehot.T, points,
                           preferred_element_type=jnp.float32), axis)
            new_centers = jnp.where(
                (counts > 0)[:, None],
                sums / jnp.maximum(counts, 1.0)[:, None], centers)
            cost = jax.lax.psum(
                jnp.sum(w * jnp.maximum(jnp.min(d, axis=1), 0.0)), axis)
            return new_centers, cost

        centers, costs = jax.lax.scan(step, centers0, None,
                                      length=iterations)
        return centers, costs[-1]

    sharded = shard_map(
        _run, mesh=mesh,
        in_specs=(P(axis), P(axis), P()),
        out_specs=(P(), P()))
    return jax.jit(sharded)


def train_kmeans_distributed(points: np.ndarray, k: int, iterations: int,
                             mesh: Mesh, runs: int = 1,
                             initialization: str = K_MEANS_PARALLEL,
                             seed: int | None = None,
                             axis: str = "d") -> list[ClusterInfo]:
    """Multi-device drop-in for train_kmeans (same model semantics)."""
    points = np.asarray(points, dtype=np.float32)
    n = len(points)
    if k < 2:
        raise ValueError("k must be > 1")
    if n < k:
        raise ValueError(f"fewer points ({n}) than clusters ({k})")
    rng = np.random.default_rng(
        RandomManager.random_seed() if seed is None else seed)
    n_dev = mesh.devices.size
    n_pad = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
    padded = np.zeros((n_pad, points.shape[1]), dtype=np.float32)
    padded[:n] = points
    weights = np.zeros(n_pad, dtype=np.float32)
    weights[:n] = 1.0

    row = NamedSharding(mesh, P(axis))
    dev_points = jax.device_put(padded, row)
    dev_w = jax.device_put(weights, row)
    step = make_lloyd_step(mesh, k, iterations, axis)

    best_centers, best_cost = None, math.inf
    for run in range(max(1, runs)):
        if initialization == RANDOM:
            centers0 = points[rng.choice(n, size=k, replace=False)]
        elif initialization == K_MEANS_PARALLEL:
            centers0 = _init_parallel(points, k, rng)
        else:
            raise ValueError(
                f"unknown initialization strategy: {initialization}")
        centers, cost = jax.device_get(
            step(dev_points, dev_w, jnp.asarray(centers0)))
        _log.info("dist k-means run %d/%d cost %.4f", run + 1, runs, cost)
        if cost < best_cost:
            best_centers, best_cost = centers, float(cost)

    idx, _ = assign_points(points, best_centers)
    counts = np.bincount(idx, minlength=k)
    return [ClusterInfo(i, best_centers[i], max(1, int(counts[i])))
            for i in range(k)]
