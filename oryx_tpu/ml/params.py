"""Hyperparameter value ranges and grid-search combination chooser.

Reference: framework/oryx-ml/src/main/java/com/cloudera/oryx/ml/param/
HyperParams.java (fromConfig :74, chooseHyperParameterCombos :123,
chooseValuesPerHyperParam :180), ContinuousRange.java:64,
DiscreteRange.java:72, ContinuousAround.java, DiscreteAround.java,
Unordered.java:47, HyperParamValues.java:35.
"""

from __future__ import annotations

import abc
from typing import Any, Sequence

from ..common.config import Config
from ..common.rand import RandomManager

__all__ = [
    "HyperParamValues", "fixed", "range_values", "around", "unordered",
    "from_config", "choose_hyper_parameter_combos", "choose_values_per_hyperparam",
]

_MAX_COMBOS = 65536


class HyperParamValues(abc.ABC):
    """A range of values of one hyperparameter to try."""

    @abc.abstractmethod
    def get_trial_values(self, num: int) -> list:
        """``num`` representative values spanning the range."""


class _Fixed(HyperParamValues):
    def __init__(self, value):
        self._value = value

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        return [self._value]

    def __repr__(self):  # pragma: no cover
        return f"Fixed[{self._value}]"


class _ContinuousRange(HyperParamValues):
    def __init__(self, lo: float, hi: float):
        if lo > hi:
            raise ValueError("min > max")
        self._lo, self._hi = lo, hi

    def get_trial_values(self, num: int) -> list[float]:
        assert num > 0
        lo, hi = self._lo, self._hi
        if hi == lo:
            return [lo]
        if num == 1:
            return [(lo + hi) / 2.0]
        step = (hi - lo) / (num - 1)
        vals = [lo + i * step for i in range(num - 1)]
        vals.append(hi)
        return vals


class _DiscreteRange(HyperParamValues):
    def __init__(self, lo: int, hi: int):
        if lo > hi:
            raise ValueError("min > max")
        self._lo, self._hi = lo, hi

    def get_trial_values(self, num: int) -> list[int]:
        assert num > 0
        lo, hi = self._lo, self._hi
        if hi == lo:
            return [lo]
        if num == 1:
            return [(lo + hi) // 2]
        if num == 2:
            return [lo, hi]
        if num > hi - lo:
            return list(range(lo, hi + 1))
        step = (hi - lo) / (num - 1)
        vals: list[int] = [lo]
        for _ in range(num - 2):
            vals.append(int(round(vals[-1] + step)))
        vals.append(hi)
        return vals


class _ContinuousAround(HyperParamValues):
    def __init__(self, around_val: float, step: float):
        if step <= 0:
            raise ValueError("step must be positive")
        self._around, self._step = around_val, step

    def get_trial_values(self, num: int) -> list[float]:
        assert num > 0
        if num == 1:
            return [self._around]
        start = self._around - ((num - 1) / 2.0) * self._step
        vals = [start + i * self._step for i in range(num)]
        if num % 2 != 0:
            vals[num // 2] = self._around  # keep middle value exact
        return vals


class _DiscreteAround(HyperParamValues):
    def __init__(self, around_val: int, step: int):
        if step <= 0:
            raise ValueError("step must be positive")
        self._around, self._step = around_val, step

    def get_trial_values(self, num: int) -> list[int]:
        assert num > 0
        if num == 1:
            return [self._around]
        start = self._around - ((num - 1) * self._step // 2)
        return [start + i * self._step for i in range(num)]


class _Unordered(HyperParamValues):
    def __init__(self, values: Sequence):
        if not values:
            raise ValueError("no values")
        self._values = list(values)

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        return self._values[:num] if num < len(self._values) else list(self._values)


def fixed(value) -> HyperParamValues:
    return _Fixed(value)


def range_values(lo, hi) -> HyperParamValues:
    if isinstance(lo, int) and isinstance(hi, int):
        return _DiscreteRange(lo, hi)
    return _ContinuousRange(float(lo), float(hi))


def around(value, step) -> HyperParamValues:
    if isinstance(value, int) and isinstance(step, int):
        return _DiscreteAround(value, step)
    return _ContinuousAround(float(value), float(step))


def unordered(values: Sequence) -> HyperParamValues:
    return _Unordered(values)


def from_config(config: Config, key: str) -> HyperParamValues:
    """Interpret a config value as fixed / range / unordered
    (reference: HyperParams.fromConfig :74).  A two-element list of
    numbers is a range; any other list is unordered; a scalar is fixed
    (int preferred over double over string)."""
    v = config.get(key)
    if isinstance(v, list):
        if len(v) == 2:
            # only parse failures fall through to 'unordered'; a reversed
            # numeric range like [8, 2] is a config error and propagates
            try:
                lo, hi = int(str(v[0])), int(str(v[1]))
            except ValueError:
                try:
                    lo, hi = float(str(v[0])), float(str(v[1]))
                except ValueError:
                    return unordered(list(v))
            return range_values(lo, hi)
        # unordered values keep their native types (ints stay ints)
        return unordered(list(v))
    s = str(v)
    try:
        return fixed(int(s))
    except ValueError:
        pass
    try:
        return fixed(float(s))
    except ValueError:
        pass
    return unordered([s])


def choose_values_per_hyperparam(num_params: int, candidates: int) -> int:
    """Smallest v with v^num_params >= candidates
    (reference: HyperParams.chooseValuesPerHyperParam :180)."""
    if num_params < 1:
        return 0
    v = 0
    total = 0
    while total < candidates:
        v += 1
        total = v ** num_params
    return v


def choose_hyper_parameter_combos(ranges: Sequence[HyperParamValues],
                                  how_many: int,
                                  per_param: int) -> list[list]:
    """Cartesian grid of trial values, randomly subsampled/shuffled to at
    most ``how_many`` combos (reference:
    HyperParams.chooseHyperParameterCombos :123)."""
    if how_many <= 0:
        raise ValueError("how_many must be positive")
    if per_param < 0:
        raise ValueError("per_param must be non-negative")
    num_params = len(ranges)
    if num_params == 0 or per_param == 0:
        return [[]]
    if per_param ** num_params > _MAX_COMBOS:
        raise ValueError(f"too many combinations: {per_param}^{num_params}")

    param_ranges = [r.get_trial_values(per_param) for r in ranges]
    total = 1
    for vals in param_ranges:
        total *= len(vals)

    combos: list[list] = []
    for combo in range(total):
        combination = []
        idx = combo
        for vals in param_ranges:
            combination.append(vals[idx % len(vals)])
            idx //= len(vals)
        combos.append(combination)

    rng = RandomManager.random()
    if how_many >= total:
        rng.shuffle(combos)
        return combos
    chosen = rng.permutation(total)[:how_many]
    result = [combos[i] for i in chosen]
    rng.shuffle(result)
    return result
