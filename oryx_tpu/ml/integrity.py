"""Model-integrity primitives: the numerical-trust boundary of the
update path.

The lambda loop moves models through several hand-offs — trainer →
PMML + factor artifacts → update topic → speed/serving managers — and
PR 1 made the *transport* of those hand-offs resilient.  This module is
the *content* side: a model that arrives intact but carries NaN/Inf
factors (a diverged candidate, a truncated artifact, a poison UP
message) is just as fatal to serving quality as a lost message, and
silently worse because nothing times out.  Every producer-side gate
(`ml/mlupdate.py` pre-publish validation) and consumer-side gate
(speed/serving managers, `app/pmml_utils.py`) shares these checks so
"finite" means the same thing at every hand-off.

Reference: MLlib-side training is f64 and MLUpdate.java:254-296 skips
NaN *evals*; nothing in the reference validates factor payloads because
JVM double arithmetic rarely manufactures NaN at these scales.  The f32
device path can, so the gates are load-bearing here.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "ModelIntegrityError", "NumericalDivergenceError",
    "is_finite_array", "check_finite_array",
]


class ModelIntegrityError(Exception):
    """A model artifact or update payload failed an integrity check
    (non-finite factors, truncated/corrupt document, missing fields).
    Consumers treat it like a lost message: log, count, keep serving
    the previous model."""


class NumericalDivergenceError(ModelIntegrityError):
    """Training diverged to non-finite factors and every rung of the
    rescue ladder (f32 -> f64 -> escalated regularization) failed."""


def is_finite_array(a) -> bool:
    """True when every element is finite (empty arrays are finite)."""
    a = np.asarray(a)
    return a.size == 0 or bool(np.all(np.isfinite(a)))


def check_finite_array(name: str, a) -> None:
    """Raise ModelIntegrityError when ``a`` holds NaN/Inf."""
    a = np.asarray(a)
    if not is_finite_array(a):
        bad = int(a.size - np.count_nonzero(np.isfinite(a)))
        raise ModelIntegrityError(
            f"{name} has {bad} non-finite entries (shape {a.shape})")
