"""In-tree float64 NumPy ALS oracle: the slow, trusted quality
reference.

The north-star quality gate (SURVEY §7) asks for RMSE/AUC parity with
the reference's MLlib ALS at equal hyperparameters.  MLlib cannot run
in this environment, so this module is the strongest available
substitute: a deliberately simple, loop-per-row, float64 NumPy
implementation of the same objective the TPU trainer optimizes —

  implicit:  min Σ_ui c_ui (p_ui - x_u·y_i)^2 + λ Σ_u n_u|x_u|^2 + ...
             c = 1 + α|r|,  p = 1 if r > 0 else 0
             (Hu, Koren & Volinsky 2008, the paper cited at reference
             ALSUpdate.java:60-68)
  explicit:  min Σ_observed (r_ui - x_u·y_i)^2 + λ n_u |x_u|^2 + ...
             (ALS-WR per-row-count λ scaling, as MLlib does)

Design constraints that make it an oracle rather than a second trainer:

- float64 everywhere (MLlib's working precision, ALSUpdate.java:88-152);
- no batching, no padding, no device code, no shared helpers with
  `app/als/trainer.py` — an error there cannot be mirrored here;
- one plain least-squares solve per row per half-sweep, readable
  against the paper's equations in a few minutes.

`tests/test_numerics.py` (marker: numerics, tier-1) asserts the TPU
trainer reaches oracle RMSE/AUC within tolerance at equal hyperparams.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

__all__ = ["OracleModel", "train_als_oracle"]


class OracleModel(NamedTuple):
    X: np.ndarray  # (n_users, k) float64
    Y: np.ndarray  # (n_items, k) float64


def _solve_side(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                n_rows: int, opposite: np.ndarray, lam: float,
                alpha: float, implicit: bool) -> np.ndarray:
    """Solve every row's factor given the opposite side's factors: the
    normal equations of the (implicit or explicit) objective, one row
    at a time in float64."""
    k = opposite.shape[1]
    out = np.zeros((n_rows, k), dtype=np.float64)
    gramian = opposite.T @ opposite if implicit else None
    eye = np.eye(k, dtype=np.float64)
    order = np.argsort(rows, kind="stable")
    srows, scols, svals = rows[order], cols[order], vals[order]
    bounds = np.searchsorted(srows, np.arange(n_rows + 1))
    for r in range(n_rows):
        lo, hi = bounds[r], bounds[r + 1]
        if lo == hi:
            continue  # no interactions: zero factor (trainer parity)
        Yr = opposite[scols[lo:hi]]       # (n_r, k)
        v = svals[lo:hi]
        n_r = hi - lo
        if implicit:
            # A = Y^T Y + Y_r^T diag(c-1) Y_r + λ n_r I,  b = Y_r^T (c p)
            c_minus_1 = alpha * np.abs(v)
            a = gramian + Yr.T @ (Yr * c_minus_1[:, None])
            b = Yr.T @ ((1.0 + c_minus_1) * (v > 0.0))
        else:
            a = Yr.T @ Yr
            b = Yr.T @ v
        a += lam * n_r * eye
        out[r] = np.linalg.solve(a, b)
    return out


def train_als_oracle(users: np.ndarray, items: np.ndarray,
                     values: np.ndarray, n_users: int, n_items: int,
                     features: int, lam: float, alpha: float,
                     implicit: bool, iterations: int,
                     seed: int = 0) -> OracleModel:
    """Factor the interaction COO in float64.

    Same init scheme as the TPU trainer (normalized gaussian / sqrt(k)
    item factors, user side solved first), so a run at equal
    hyperparameters is comparable apples-to-apples.
    """
    users = np.asarray(users, dtype=np.int64)
    items = np.asarray(items, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    rng = np.random.default_rng(seed)
    Y = rng.standard_normal((n_items, features)) / np.sqrt(features)
    X = np.zeros((n_users, features), dtype=np.float64)
    for _ in range(iterations):
        X = _solve_side(users, items, values, n_users, Y, lam, alpha,
                        implicit)
        Y = _solve_side(items, users, values, n_items, X, lam, alpha,
                        implicit)
    return OracleModel(X, Y)
