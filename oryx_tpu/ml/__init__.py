from .mlupdate import MLUpdate  # noqa: F401
from . import params  # noqa: F401
