"""The batch-ML training loop: hyperparameter search, train, evaluate,
pick best, publish.

Reference: framework/oryx-ml/src/main/java/com/cloudera/oryx/ml/
MLUpdate.java:60-382 — runUpdate :161 (cache, combos, parallel build,
atomic rename, MODEL vs MODEL-REF publish, publishAdditionalModelData
hook), findBestCandidatePath :254 (NaN-eval handling, eval-disabled
case, threshold gate), buildAndEval :299, splitTrainTest :346.
"""

from __future__ import annotations

import abc
import contextlib
import logging
import math
import os
import time
from typing import Sequence
from xml.etree.ElementTree import Element

from ..common import pmml as pmml_io
from ..common import store
from ..common.config import Config
from ..common.io_utils import mkdirs
from ..common.lang import collect_in_parallel
from ..common.rand import RandomManager
from ..kafka.api import KEY_MODEL, KEY_MODEL_REF, KeyMessage, TopicProducer
from . import params as hp
from ..api.batch import BatchLayerUpdate

_log = logging.getLogger(__name__)

MODEL_FILE_NAME = "model.pmml.xml"

__all__ = ["MLUpdate", "MODEL_FILE_NAME"]


class MLUpdate(BatchLayerUpdate, abc.ABC):
    """Subclasses supply model building and evaluation; this class runs
    the per-generation loop."""

    def __init__(self, config: Config):
        self.config = config
        self.test_fraction = config.get_double("oryx.ml.eval.test-fraction")
        self.candidates = config.get_int("oryx.ml.eval.candidates")
        self.eval_parallelism = config.get_int("oryx.ml.eval.parallelism")
        self.threshold = config.get_optional_double("oryx.ml.eval.threshold")
        self.max_message_size = config.get_int("oryx.update-topic.message.max-size")
        # optional per-generation device trace (SURVEY §5.1: the TPU
        # answer to the reference's per-layer Spark UI is a JAX profiler
        # trace viewable in TensorBoard/Perfetto)
        self.profile_dir = config.get_optional_string("oryx.ml.profile-dir")
        if not 0.0 <= self.test_fraction <= 1.0:
            raise ValueError("test-fraction must be in [0,1]")
        if self.candidates < 1:
            raise ValueError("candidates must be positive")
        if self.test_fraction == 0.0 and self.candidates > 1:
            _log.info("Building multiple candidates requires test-fraction > 0; "
                      "building one model")
            self.candidates = 1

    # -- subclass contract --------------------------------------------------

    @abc.abstractmethod
    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        ...

    @abc.abstractmethod
    def build_model(self, train_data: Sequence[KeyMessage],
                    hyper_parameters: list, candidate_path: str) -> Element | None:
        """Train on ``train_data`` with the given hyperparameters; return a
        PMML document (side artifacts may be written under
        ``candidate_path``)."""

    @abc.abstractmethod
    def evaluate(self, model: Element, candidate_path: str,
                 test_data: Sequence[KeyMessage],
                 train_data: Sequence[KeyMessage]) -> float:
        """Higher is better (negate error metrics)."""

    def validate_model(self, model: Element, candidate_path: str) -> bool:
        """Pre-publish integrity gate: return False to reject the
        candidate outright (it can never be selected or published).
        Subclasses override to check model content — e.g. ALS verifies
        every factor artifact is finite.  The default accepts."""
        return True

    def can_publish_additional_model_data(self) -> bool:
        return False

    def prepare_model_ref_payload(self, model: Element | None,
                                  model_path: str,
                                  new_data: Sequence[KeyMessage],
                                  past_data: Sequence[KeyMessage]) -> str:
        """The MODEL-REF message payload for a too-large-to-inline
        model.  The default is the reference contract — the bare
        storage path of the PMML file.  Apps with a sharded
        distribution story (ALS) override to write per-slice artifacts
        next to the model and return a manifest-carrying envelope
        (app/als/slices.py), so consumers bulk-load their slice
        instead of replaying a full UP stream."""
        return model_path

    def publish_additional_model_data(self, model: Element,
                                      new_data: Sequence[KeyMessage],
                                      past_data: Sequence[KeyMessage],
                                      model_path: str,
                                      model_update_topic: TopicProducer) -> None:
        pass

    def split_new_data_to_train_test(
            self, new_data: Sequence[KeyMessage]
    ) -> tuple[list[KeyMessage], list[KeyMessage]]:
        """Random split; apps override for e.g. time-based splits
        (reference: MLUpdate.splitNewDataToTrainTest)."""
        rng = RandomManager.random()
        mask = rng.random(len(new_data)) < self.test_fraction
        train = [km for km, m in zip(new_data, mask) if not m]
        test = [km for km, m in zip(new_data, mask) if m]
        return train, test

    # -- the loop -----------------------------------------------------------

    def run_update(self, timestamp_ms: int,
                   new_data: Sequence[KeyMessage],
                   past_data: Sequence[KeyMessage],
                   model_dir: str,
                   model_update_topic: TopicProducer | None) -> None:
        new_data = list(new_data or [])
        past_data = list(past_data or [])

        ranges = self.get_hyper_parameter_values()
        per_param = hp.choose_values_per_hyperparam(len(ranges), self.candidates)
        combos = hp.choose_hyper_parameter_combos(ranges, self.candidates, per_param)

        model_dir = store.mkdirs(model_dir)
        candidates_path = store.join(model_dir, ".temporary",
                                     str(int(time.time() * 1000)))
        store.mkdirs(candidates_path)

        if self.profile_dir:
            import jax
            trace = jax.profiler.trace(
                mkdirs(os.path.join(self.profile_dir, str(timestamp_ms))))
        else:
            trace = contextlib.nullcontext()
        with trace:
            best_candidate = self._find_best_candidate_path(
                new_data, past_data, combos, candidates_path)

        final_path = store.join(model_dir, str(int(time.time() * 1000)))
        if best_candidate is None:
            _log.info("Unable to build any model")
        else:
            store.rename(best_candidate, final_path)  # atomic publish
        store.delete_recursively(store.join(model_dir, ".temporary"))

        if model_update_topic is None:
            _log.info("No update topic configured, not publishing models")
        else:
            best_model_path = store.join(final_path, MODEL_FILE_NAME)
            if store.exists(best_model_path):
                size = store.getsize(best_model_path)
                needed = self.can_publish_additional_model_data()
                not_too_large = size <= self.max_message_size
                best_model = None
                if needed or not_too_large:
                    best_model = pmml_io.read(best_model_path)
                if not_too_large:
                    model_update_topic.send(KEY_MODEL, pmml_io.to_string(best_model))
                else:
                    model_update_topic.send(
                        KEY_MODEL_REF,
                        self.prepare_model_ref_payload(
                            best_model, best_model_path, new_data,
                            past_data))
                if needed:
                    self.publish_additional_model_data(
                        best_model, new_data, past_data, final_path,
                        model_update_topic)

    def _find_best_candidate_path(self, new_data, past_data, combos,
                                  candidates_path: str) -> str | None:
        results = collect_in_parallel(
            self.candidates,
            lambda i: self._build_and_eval(i, combos, new_data, past_data,
                                           candidates_path),
            min(self.eval_parallelism, self.candidates))

        best_path, best_eval = None, float("-inf")
        for path, eval_ in results:
            if path is None or not store.exists(path):
                continue
            if math.isfinite(eval_):
                # argmax strictly over FINITE evals: NaN is the
                # reference's skip semantics (MLUpdate.java:254-296),
                # and +/-Inf is a degenerate metric no candidate may
                # win with — garbage never outranks a real model
                if eval_ > best_eval:
                    _log.info("Best eval / model path is now %s / %s", eval_, path)
                    best_eval, best_path = eval_, path
            elif best_path is None and self.test_fraction == 0.0:
                # eval disabled: keep the one model that was built
                best_path = path
        if self.threshold is not None and best_eval < self.threshold:
            _log.info("Best model had eval %s, below threshold %s; discarding",
                      best_eval, self.threshold)
            best_path = None
        return best_path

    def _build_and_eval(self, i: int, combos, new_data, past_data,
                        candidates_path: str) -> tuple[str | None, float]:
        hyper_parameters = combos[i % len(combos)]
        candidate_path = store.join(candidates_path, str(i))
        _log.info("Building candidate %d with params %s", i, hyper_parameters)

        train, test = self._split_train_test(new_data, past_data)
        eval_ = float("nan")
        if not train:
            _log.info("No train data to build a model")
            return candidate_path, eval_
        model = self.build_model(train, hyper_parameters, candidate_path)
        if model is None:
            _log.info("Unable to build a model")
            return candidate_path, eval_
        store.mkdirs(candidate_path)
        model_path = store.join(candidate_path, MODEL_FILE_NAME)
        pmml_io.write(model, model_path)
        # pre-publish integrity gate: a candidate that fails validation
        # is dropped entirely (path=None) so no selection branch — not
        # even the eval-disabled one — can ever publish it
        if not self.validate_model(model, candidate_path):
            _log.warning("Model for params %s failed integrity validation; "
                         "rejecting candidate %s", hyper_parameters, i)
            return None, eval_
        if not test:
            _log.info("No test data available to evaluate model")
        else:
            eval_ = self.evaluate(model, candidate_path, test, train)
        _log.info("Model eval for params %s: %s (%s)", hyper_parameters, eval_,
                  candidate_path)
        return candidate_path, eval_

    def _split_train_test(self, new_data, past_data):
        if self.test_fraction <= 0.0:
            return list(new_data) + list(past_data), []
        if self.test_fraction >= 1.0:
            return list(past_data), list(new_data)
        if not new_data:
            return list(past_data), []
        new_train, test = self.split_new_data_to_train_test(new_data)
        return list(new_train) + list(past_data), test
