"""Speed layer user contract.

Reference: framework/oryx-api/src/main/java/com/cloudera/oryx/api/speed/
SpeedModelManager.java:37-68, SpeedModel.java:23,
AbstractSpeedModelManager.java:36.
"""

from __future__ import annotations

import abc
from typing import Iterable, Iterator, Sequence

from ..kafka.api import KeyMessage

__all__ = ["SpeedModel", "SpeedModelManager", "AbstractSpeedModelManager"]


class SpeedModel(abc.ABC):
    """In-memory model state of the speed layer."""

    @abc.abstractmethod
    def get_fraction_loaded(self) -> float:
        """Approximate fraction of the model loaded so far (readiness gate)."""


class SpeedModelManager(abc.ABC):
    """Consumes models/updates from the update topic and produces deltas
    from new input.  Configured via ``oryx.speed.model-manager-class``."""

    @abc.abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None:
        """Read model + update messages until the stream ends; maintain
        the in-memory speed model."""

    @abc.abstractmethod
    def build_updates(self, new_data: Sequence[KeyMessage]) -> Iterable[str]:
        """Derive model deltas from one micro-batch of input; each
        returned string is sent with key "UP"."""

    def close(self) -> None:
        pass


class AbstractSpeedModelManager(SpeedModelManager):
    """Adapts the stream contract to a per-message callback
    (reference: AbstractSpeedModelManager.java:36)."""

    def consume(self, updates: Iterator[KeyMessage]) -> None:
        for km in updates:
            self.consume_key_message(km.key, km.message)

    @abc.abstractmethod
    def consume_key_message(self, key: str | None, message: str) -> None: ...
