"""Batch layer user contract.

Reference: framework/oryx-api/src/main/java/com/cloudera/oryx/api/batch/
BatchLayerUpdate.java:38-59.  Where the reference hands the update
implementation Spark RDDs, this framework hands it plain in-memory
sequences of (key, message) pairs — the batch layer's data plane is the
host, and heavy compute is expected to go through JAX device arrays
built from these sequences.
"""

from __future__ import annotations

import abc
from typing import Sequence

from ..kafka.api import KeyMessage, TopicProducer

__all__ = ["BatchLayerUpdate"]


class BatchLayerUpdate(abc.ABC):
    """Implementations define how a new batch of data updates the model.

    Configured via ``oryx.batch.update-class`` (import path); may expose
    a constructor accepting the Config.
    """

    @abc.abstractmethod
    def run_update(self,
                   timestamp_ms: int,
                   new_data: Sequence[KeyMessage],
                   past_data: Sequence[KeyMessage],
                   model_dir: str,
                   model_update_topic: TopicProducer | None) -> None:
        """Run one generation: combine new and historical data into a new
        model, written under ``model_dir`` and announced on the update
        topic."""
