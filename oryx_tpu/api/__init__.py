from .batch import BatchLayerUpdate  # noqa: F401
from .serving import (AbstractServingModelManager, HasCSV,  # noqa: F401
                      OryxServingException, ServingModel, ServingModelManager)
from .speed import (AbstractSpeedModelManager, SpeedModel,  # noqa: F401
                    SpeedModelManager)
