"""Serving layer user contract.

Reference: framework/oryx-api/src/main/java/com/cloudera/oryx/api/serving/
ServingModelManager.java:35-76, ServingModel.java:23,
AbstractServingModelManager.java:35, OryxServingException.java:26,
HasCSV.java:25.
"""

from __future__ import annotations

import abc
from typing import Any, Iterator

from ..common.config import Config
from ..kafka.api import KeyMessage

__all__ = [
    "ServingModel", "ServingModelManager", "AbstractServingModelManager",
    "OryxServingException", "HasCSV",
]


class ServingModel(abc.ABC):
    """In-memory model state of the serving layer."""

    @abc.abstractmethod
    def get_fraction_loaded(self) -> float: ...


class ServingModelManager(abc.ABC):
    """Consumes models/updates from the update topic and exposes the
    current servable model.  Configured via
    ``oryx.serving.model-manager-class``."""

    @abc.abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None: ...

    @abc.abstractmethod
    def get_model(self) -> Any: ...

    def get_config(self) -> Config | None:
        return None

    def is_read_only(self) -> bool:
        return False

    def close(self) -> None:
        pass


class AbstractServingModelManager(ServingModelManager):
    """Adapts the stream contract to a per-message callback
    (reference: AbstractServingModelManager.java:35)."""

    def __init__(self, config: Config):
        self._config = config
        self._read_only = config.get_bool("oryx.serving.api.read-only")

    def get_config(self) -> Config:
        return self._config

    def is_read_only(self) -> bool:
        return self._read_only

    def consume(self, updates: Iterator[KeyMessage]) -> None:
        for km in updates:
            self.consume_key_message(km.key, km.message)

    @abc.abstractmethod
    def consume_key_message(self, key: str | None, message: str) -> None: ...


class OryxServingException(Exception):
    """An error with an HTTP status, mapped to a plain-text error response
    (reference: OryxServingException.java:26).  ``headers`` optionally
    rides extra response headers out with the error page — the write
    path's shed responses carry ``Retry-After`` this way."""

    def __init__(self, status: int, message: str = "",
                 headers: dict | None = None):
        super().__init__(message)
        self.status = status
        self.headers = headers


class HasCSV(abc.ABC):
    """Response DTOs that know how to render as a CSV line
    (reference: HasCSV.java:25)."""

    @abc.abstractmethod
    def to_csv(self) -> str: ...
