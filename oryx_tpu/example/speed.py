"""Example speed layer: incremental co-occurrence counts.

Reference: app/example/src/main/java/com/cloudera/oryx/example/speed/
ExampleSpeedModelManager.java:37 — MODEL replaces the in-memory map;
each micro-batch counts the batch's distinct-other-words, adds them to
the map, and emits "word,newCount" UP messages.
"""

from __future__ import annotations

import json
import threading
from typing import Iterable, Sequence

from ..api.speed import AbstractSpeedModelManager
from ..common.config import Config
from ..kafka.api import KEY_MODEL, KEY_UP, KeyMessage
from .batch import count_distinct_other_words

__all__ = ["ExampleSpeedModelManager"]


class ExampleSpeedModelManager(AbstractSpeedModelManager):

    def __init__(self, config: Config):
        self._words: dict[str, int] = {}
        self._lock = threading.Lock()

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == KEY_MODEL:
            model = json.loads(message)
            with self._lock:
                self._words.clear()
                self._words.update(model)
        elif key == KEY_UP:
            pass  # hearing our own updates
        else:
            raise ValueError(f"Bad key {key}")

    def build_updates(self, new_data: Sequence[KeyMessage]) -> Iterable[str]:
        out = []
        for word, count in count_distinct_other_words(new_data).items():
            with self._lock:
                new_count = self._words.get(word, 0) + count
                self._words[word] = new_count
            out.append(f"{word},{new_count}")
        return out
