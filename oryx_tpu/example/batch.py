"""Example batch update: distinct co-occurring word counts.

Reference: app/example/src/main/java/com/cloudera/oryx/example/batch/
ExampleBatchLayerUpdate.java:39 — per generation, over new+past data:
for every line, form all ordered (word, otherWord) pairs of distinct
tokens, deduplicate pairs globally, count per word, publish the whole
map as an inline JSON "MODEL" message.
"""

from __future__ import annotations

import json
from typing import Sequence

from ..api.batch import BatchLayerUpdate
from ..common.config import Config
from ..kafka.api import KEY_MODEL, KeyMessage, TopicProducer

__all__ = ["ExampleBatchLayerUpdate", "count_distinct_other_words"]


def count_distinct_other_words(
        data: Sequence[KeyMessage]) -> dict[str, int]:
    pairs: set[tuple[str, str]] = set()
    for km in data:
        tokens = set(km.message.split(" "))
        for a in tokens:
            for b in tokens:
                if a != b:
                    pairs.add((a, b))
    counts: dict[str, int] = {}
    for a, _ in pairs:
        counts[a] = counts.get(a, 0) + 1
    return counts


class ExampleBatchLayerUpdate(BatchLayerUpdate):

    def __init__(self, config: Config):
        pass

    def run_update(self, timestamp_ms: int,
                   new_data: Sequence[KeyMessage],
                   past_data: Sequence[KeyMessage],
                   model_dir: str,
                   model_update_topic: TopicProducer | None) -> None:
        all_data = list(new_data) + list(past_data or [])
        model = count_distinct_other_words(all_data)
        if model_update_topic is not None:
            model_update_topic.send(KEY_MODEL, json.dumps(model))
