"""Example serving layer: model manager + /distinct and /add resources.

Reference: app/example/src/main/java/com/cloudera/oryx/example/serving/
ExampleServingModelManager.java:35 (MODEL replaces the map, UP applies
"word,count"), Distinct.java:35 (GET /distinct and /distinct/{word}),
Add.java:36 (POST /add/{line} writes the input topic).
"""

from __future__ import annotations

import json
import threading

from ..api.serving import (AbstractServingModelManager, OryxServingException,
                           ServingModel)
from ..common.config import Config
from ..kafka.api import KEY_MODEL, KEY_UP
from ..lambda_rt.http import Request, Route
from ..serving import console
from ..serving.framework import get_serving_model, send_input

__all__ = ["ExampleServingModel", "ExampleServingModelManager", "ROUTES"]


class ExampleServingModel(ServingModel):

    def __init__(self, words: dict[str, int], lock: threading.Lock):
        self._words = words
        self._lock = lock

    def get_words(self) -> dict[str, int]:
        with self._lock:
            return dict(self._words)

    def get_count(self, word: str) -> int | None:
        with self._lock:
            return self._words.get(word)

    def get_fraction_loaded(self) -> float:
        return 1.0


class ExampleServingModelManager(AbstractServingModelManager):

    def __init__(self, config: Config):
        super().__init__(config)
        self._words: dict[str, int] = {}
        self._lock = threading.Lock()

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == KEY_MODEL:
            model = json.loads(message)
            with self._lock:
                self._words.clear()
                self._words.update(model)
        elif key == KEY_UP:
            word, count = message.split(",")
            with self._lock:
                self._words[word] = int(count)
        else:
            raise ValueError(f"Bad key {key}")

    def get_model(self) -> ExampleServingModel:
        return ExampleServingModel(self._words, self._lock)


def _distinct(req: Request):
    return get_serving_model(req).get_words()


def _distinct_word(req: Request):
    count = get_serving_model(req).get_count(req.params["word"])
    if count is None:
        raise OryxServingException(400, "No such word")
    return count


def _add(req: Request):
    send_input(req, req.params["line"])
    return None


ROUTES = [
    Route("GET", "/distinct", _distinct),
    Route("GET", "/distinct/{word}", _distinct_word),
    Route("POST", "/add/{line}", _add, mutates=True),
    console.console_route("Word Count Example", [
        console.Endpoint("/distinct"),
        console.Endpoint("/distinct/{0}", ("word",)),
        console.Endpoint("/add/{0}", ("line",), method="POST"),
        console.Endpoint("/ready"),
    ]),
]
