"""Word-count example custom app: the minimal end-to-end demonstration
of the framework API (reference: app/example/ — a custom app counts,
for each word, the distinct other words that co-occur on a line)."""
