"""``python -m oryx_tpu``: the operator CLI (see deploy/main.py)."""

import sys

from .deploy.main import main

sys.exit(main())
