"""Replica-internal shard resources: the scatter-gather targets.

Registered by the serving layer when ``oryx.cluster.enabled`` is true.
Every response carries the replica's shard coordinates and model
generation so the router can detect topology or generation drift, and
top-k rows travel as ``[id, score, ordinal]`` triples — the ordinal is
the cluster's canonical tie-break (cluster/merge.py).

Surface:

========================  ===================================================
``GET  /shard/meta``      shard coords, generation, readiness, model shape
``GET  /shard/recommend/{userID}``  local exact top-k for the user (the
                          flagship internal resource; params mirror the
                          public ``/recommend``)
``POST /shard/query``     generic local query (JSON body): kinds
                          ``recommend`` / ``recommendToMany`` /
                          ``byVector`` / ``because`` / ``mostSurprising``
                          / ``allItemIDs``
``POST /shard/vectors``   bulk user/item vector fetch (users answer from
                          the replicated store; items only when local)
``GET  /shard/yty``       this shard's partial Gramian Y_s^T Y_s — the
                          router sums shards' partials into the full YtY
                          for anonymous/context fold-in
========================  ===================================================
"""

from __future__ import annotations

import numpy as np

from ..api.serving import OryxServingException
from ..app.als.serving_model import ALSServingModel
from ..lambda_rt.http import Request, Route
from ..serving.framework import get_serving_model
from .merge import canon_sort, exact_local_top_n

__all__ = ["ROUTES"]

# ordinal for items that never came through the update-topic replay
# (models built directly in tests/benches): pushes past any real
# ordinal; the canonical order's final id key keeps it total
_NO_ORDINAL = 1 << 62


def _manager(req: Request):
    return req.context["model_manager"]


def _als_model(req: Request) -> ALSServingModel:
    model = get_serving_model(req)
    if not isinstance(model, ALSServingModel):
        raise OryxServingException(503, "Model not available yet")
    return model


def _ordinal_of(manager):
    ordinals = getattr(manager, "item_ordinals", {})
    return lambda i: ordinals.get(i, _NO_ORDINAL)


def _envelope(req: Request, manager, **extra) -> dict:
    out = {
        "shard": getattr(manager, "shard_index", 0),
        "of": getattr(manager, "shard_count", 1),
        "generation": getattr(manager, "generation", 0),
    }
    batcher = req.context.get("top_n_batcher")
    if batcher is not None:
        # measured scoring queue wait, piggybacked on every internal
        # answer: the router's admission control reads the cluster's
        # live overload state from responses it already parses, no
        # extra scrape round
        out["queue_wait_ms"] = round(batcher.recent_queue_wait_ms(), 2)
    out.update(extra)
    return out


def _rescorer_from(model, spec: dict):
    provider = model.rescorer_provider
    hook = spec.get("rescorerHook")
    if provider is None or not hook:
        return None
    args = list(spec.get("rescorerArgs") or [])
    return getattr(provider, hook)(*args,
                                   list(spec.get("rescorerParams") or []))


def _local_rows(req: Request, model, manager, how_many: int, *,
                user_vector=None, cosine_to=None, exclude=(),
                rescorer=None, allowed=None, lowest=False):
    return exact_local_top_n(
        model, _ordinal_of(manager), how_many,
        user_vector=user_vector, cosine_to=cosine_to, exclude=exclude,
        rescorer=rescorer, allowed=allowed, lowest=lowest,
        batcher=req.context.get("top_n_batcher"), deadline=req.deadline)


# -- GET /shard/recommend/{userID} -------------------------------------------

def _shard_recommend(req: Request):
    model = _als_model(req)
    manager = _manager(req)
    user_id = req.params["userID"]
    how_many = req.q_int("howMany", 10)
    if how_many <= 0:
        raise OryxServingException(400, "howMany must be positive")
    consider_known = (req.q1("considerKnownItems", "false") == "true")
    user_vector = model.get_user_vector(user_id)
    if user_vector is None:
        raise OryxServingException(404, user_id)
    exclude = set() if consider_known else model.get_known_items(user_id)
    rescorer = None
    if model.rescorer_provider is not None:
        rescorer = model.rescorer_provider.get_recommend_rescorer(
            user_id, req.q_list("rescorerParams"))
    rows = _local_rows(req, model, manager, how_many,
                       user_vector=user_vector, exclude=exclude,
                       rescorer=rescorer)
    return _envelope(req, manager, rows=rows)


# -- POST /shard/query --------------------------------------------------------

def _kind_recommend(req, model, manager, q):
    user_id = str(q["userID"])
    user_vector = model.get_user_vector(user_id)
    if user_vector is None:
        raise OryxServingException(404, user_id)
    exclude = set() if q.get("considerKnownItems") \
        else model.get_known_items(user_id)
    rescorer = None
    if model.rescorer_provider is not None:
        rescorer = model.rescorer_provider.get_recommend_rescorer(
            user_id, list(q.get("rescorerParams") or []))
    return {"rows": _local_rows(req, model, manager, int(q["howMany"]),
                                user_vector=user_vector, exclude=exclude,
                                rescorer=rescorer)}


def _kind_recommend_to_many(req, model, manager, q):
    vectors, exclude, found = [], set(), []
    for uid in q["userIDs"]:
        v = model.get_user_vector(str(uid))
        if v is not None:
            vectors.append(v)
            found.append(str(uid))
            if not q.get("considerKnownItems"):
                exclude |= model.get_known_items(str(uid))
    if not vectors:
        raise OryxServingException(404, str(q["userIDs"]))
    mean_vector = np.mean(vectors, axis=0)
    rescorer = None
    if model.rescorer_provider is not None:
        rescorer = model.rescorer_provider.get_recommend_rescorer(
            str(q["userIDs"][0]), list(q.get("rescorerParams") or []))
    return {"rows": _local_rows(req, model, manager, int(q["howMany"]),
                                user_vector=mean_vector, exclude=exclude,
                                rescorer=rescorer),
            "found": found}


def _kind_by_vector(req, model, manager, q):
    """Generic top-k against explicit query vectors (the router's
    second phase after gathering item/user vectors): one result list
    per vector.  ``cosine`` selects mean-cosine scoring with ALL the
    vectors as one query (the /similarity contract); otherwise each
    vector is an independent dot-product query."""
    vectors = [np.asarray(v, dtype=np.float32) for v in q["vectors"]]
    exclude = set(map(str, q.get("exclude") or ()))
    if q.get("excludeKnownOf"):
        exclude |= model.get_known_items(str(q["excludeKnownOf"]))
    rescorer = _rescorer_from(model, q)
    how_many = int(q["howMany"])
    if q.get("cosine"):
        rows = _local_rows(req, model, manager, how_many,
                           cosine_to=np.stack(vectors, axis=1),
                           exclude=exclude, rescorer=rescorer)
        return {"multi": [rows]}
    return {"multi": [
        _local_rows(req, model, manager, how_many, user_vector=v,
                    exclude=exclude, rescorer=rescorer,
                    lowest=bool(q.get("lowest")))
        for v in vectors]}


def _kind_because(req, model, manager, q):
    """The user's LOCAL known items ranked by cosine to an explicit
    target vector — same host math as the public /because, restricted
    to this shard's slice; the router merges shard partials."""
    user_id = str(q["userID"])
    target = np.asarray(q["vector"], dtype=np.float32)
    norm = float(np.linalg.norm(target))
    ordinal = _ordinal_of(manager)
    rows = []
    for other in model.get_known_items(user_id):
        ov = model.get_item_vector(other)
        if ov is None:
            continue  # not this shard's item (or retired)
        denom = norm * float(np.linalg.norm(ov))
        rows.append((other,
                     float(np.dot(ov, target)) / denom if denom > 0
                     else 0.0, ordinal(other)))
    return {"rows": canon_sort(rows)[:int(q["howMany"])]}


def _kind_most_surprising(req, model, manager, q):
    user_id = str(q["userID"])
    xu = model.get_user_vector(user_id)
    if xu is None:
        raise OryxServingException(404, user_id)
    ordinal = _ordinal_of(manager)
    rows = []
    for iid in model.get_known_items(user_id):
        yi = model.get_item_vector(iid)
        if yi is not None:
            rows.append((iid, float(xu @ yi), ordinal(iid)))
    return {"rows": canon_sort(rows, lowest=True)[:int(q["howMany"])]}


def _kind_all_item_ids(req, model, manager, q):
    return {"ids": model.all_item_ids()}


_KINDS = {
    "recommend": _kind_recommend,
    "recommendToMany": _kind_recommend_to_many,
    "byVector": _kind_by_vector,
    "because": _kind_because,
    "mostSurprising": _kind_most_surprising,
    "allItemIDs": _kind_all_item_ids,
}


def _shard_query(req: Request):
    import json
    model = _als_model(req)
    manager = _manager(req)
    try:
        q = json.loads(req.body.decode("utf-8"))
        kind = q["kind"]
        fn = _KINDS[kind]
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, f"bad shard query: {e}") from e
    return _envelope(req, manager, **fn(req, model, manager, q))


# -- POST /shard/vectors ------------------------------------------------------

def _shard_vectors(req: Request):
    """Bulk vector fetch.  Users answer from the replicated full store;
    items answer only when LOCAL (the router asks each id's owner
    shard), absent ids map to null."""
    import json
    model = _als_model(req)
    manager = _manager(req)
    try:
        q = json.loads(req.body.decode("utf-8"))
    except ValueError as e:
        raise OryxServingException(400, f"bad body: {e}") from e

    def fetch(ids, getter):
        out = {}
        for i in ids or ():
            v = getter(str(i))
            out[str(i)] = None if v is None else [float(x) for x in v]
        return out

    return _envelope(req, manager,
                     users=fetch(q.get("users"), model.get_user_vector),
                     items=fetch(q.get("items"), model.get_item_vector))


# -- GET /shard/yty -----------------------------------------------------------

def _shard_yty(req: Request):
    """This shard's partial Gramian: sum over shards == the full-catalog
    YtY (row-disjoint slices), which the router feeds to the fold-in
    solver for anonymous/context recommendations.  A slice-loaded
    replica answers from the manifest's precomputed per-slice partials
    (summed at load — no device scan) until a live Y write outdates
    them; otherwise the store's one-matmul vtv runs."""
    model = _als_model(req)
    manager = _manager(req)
    precomputed = getattr(manager, "partial_yty", None)
    yty = precomputed() if callable(precomputed) else None
    if yty is None:
        yty = model.Y.vtv()
    return _envelope(req, manager, features=model.features,
                     implicit=bool(model.implicit),
                     yty=[[float(x) for x in row] for row in yty])


def _shard_meta(req: Request):
    manager = _manager(req)
    model = manager.get_model()
    out = _envelope(req, manager)
    fraction = model.get_fraction_loaded() if model is not None else 0.0
    out.update(
        ready=model is not None
        and fraction >= req.context["min_model_load_fraction"],
        fraction=fraction)
    if isinstance(model, ALSServingModel):
        out.update(features=model.features, implicit=bool(model.implicit),
                   users=len(model.X), items=len(model.Y))
    return out


ROUTES = [
    Route("GET", "/shard/meta", _shard_meta),
    Route("GET", "/shard/recommend/{userID}", _shard_recommend),
    Route("POST", "/shard/query", _shard_query),
    Route("POST", "/shard/vectors", _shard_vectors),
    Route("GET", "/shard/yty", _shard_yty),
]
