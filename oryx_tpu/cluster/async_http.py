"""C10K router front end: an asyncio event loop speaking the same
``Route``/dispatcher contract as the threaded server.

The threaded front end (lambda_rt/http.py ``make_server``) spends one
OS thread per in-flight connection — the reference's Tomcat shape,
``maxThreads=400`` — so its concurrency ceiling is thread stacks, not
sockets.  PR 8 made the common answer a sub-millisecond cache hit,
which is exactly the workload an event loop multiplies: tens of
thousands of idle keep-alive connections cost file descriptors, and a
hit is served entirely ON the loop with zero thread handoffs.

Division of labor per request:

- **on-loop fast path** — HTTP/1.1 parse, route match, result-cache
  probe/lookup: a present entry renders through the same
  ``ResultCache.render`` the threaded server uses (byte-identical by
  construction) and never touches a thread.  A coalesced follower
  parks a *coroutine* on the leader's flight (woken by
  ``call_soon_threadsafe``) instead of a thread on its event.
- **bridge pool** — everything else (cache misses bound for the
  scatter, writes, admin) dispatches ``HttpApp.handle`` onto a small
  fixed executor through a buffered handler adapter.  Thread count is
  the pool size — a constant independent of connection count.  The
  pool's backlog is bounded: past it, requests shed as fast 503s
  (``async_bridge_sheds``) instead of queueing into collapse.
- **connection cap** — at ``oryx.cluster.async.max-connections`` a new
  connection gets one fast 503 and a close, never a hang
  (``async_rejected_connections``).

A watchdog task measures loop lag every tick; a handler that blocks
the loop (the one sin this architecture cannot absorb) is counted
(``async_loop_stalls``) and logged with the measured stall.  Chaos
seam ``async-loop-block`` injects exactly that sin.

Gated by ``oryx.cluster.async.enabled`` (default false); the threaded
server remains the default and the fallback.
"""

from __future__ import annotations

import asyncio
import io
import logging
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor

from ..common import clock as clockmod
from ..lambda_rt.http import (_KNOWN_METHODS, _REASONS, _render_kind,
                              render_error_page, wants_csv)
from ..resilience import faults

_log = logging.getLogger(__name__)

__all__ = ["AsyncFrontEnd"]


class _BufferedHandler:
    """The handler-surface adapter the bridge pool hands to
    ``HttpApp.handle``: the exact attribute contract of the threaded
    server's handler, with the request body pre-read (the loop owns
    the socket) and the response captured as wire bytes."""

    def __init__(self, method: str, path: str, headers: dict[str, str],
                 body: bytes, close: bool):
        self.command = method
        self.path = path
        self.headers = headers
        self.rfile = io.BytesIO(body)
        self.wfile = io.BytesIO()
        self._close = close
        self._head: list[str] = []

    def send_response(self, status: int) -> None:
        self._head.append(
            f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n")

    def send_header(self, key: str, value: str) -> None:
        self._head.append(f"{key}: {value}\r\n")

    def end_headers(self) -> None:
        self._head.append("\r\n")
        self.wfile.write("".join(self._head).encode("latin-1"))
        self._head = []


def _error_response(status: int, message: str, accept: str,
                    extra: dict[str, str] | None = None,
                    close: bool = False) -> bytes:
    payload, ctype = render_error_page(status, None, message, accept)
    head = [f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"]
    for k, v in (extra or {}).items():
        head.append(f"{k}: {v}\r\n")
    head.append(f"Content-Type: {ctype}\r\n")
    head.append(f"Content-Length: {len(payload)}\r\n")
    if close:
        head.append("Connection: close\r\n")
    head.append("\r\n")
    return "".join(head).encode("latin-1") + payload


class AsyncFrontEnd:
    """start()/shutdown() around the event loop, run on one background
    thread so the router's lifecycle contract is unchanged."""

    def __init__(self, app, port: int, config, ssl_context=None):
        c = "oryx.cluster.async"
        self.app = app
        self.requested_port = port
        self.ssl_context = ssl_context
        self.max_connections = config.get_int(f"{c}.max-connections")
        self.bridge_workers = max(1, config.get_int(
            f"{c}.bridge-workers"))
        # past this many queued-or-running bridged requests the front
        # end sheds instead of queueing (the executor's queue is
        # unbounded; the collapse mode of an un-gated front end)
        self.bridge_backlog = self.bridge_workers * 4
        self.watchdog_interval = config.get_int(
            f"{c}.watchdog-interval-ms") / 1000.0
        self.watchdog_stall = config.get_int(
            f"{c}.watchdog-stall-ms") / 1000.0
        self._bridge = ThreadPoolExecutor(
            max_workers=self.bridge_workers,
            thread_name_prefix="router-bridge")
        self.port: int | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server = None
        self._thread: threading.Thread | None = None
        self._writers: set = set()
        self._started = threading.Event()
        self._startup_error: BaseException | None = None
        # loop-owned counters (single-threaded mutation on the loop;
        # reads from /metrics gauge closures are torn-value safe)
        self.open_connections = 0
        self.bridge_inflight = 0
        self.loop_stalls = 0
        self.loop_lag_ms = 0.0
        self.rejected_connections = 0
        self.bridge_sheds = 0
        self.fast_hits = 0
        self.fast_coalesced = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="RouterAsyncLoop")
        self._thread.start()
        self._started.wait(30.0)
        if self._startup_error is not None:
            raise self._startup_error
        if self.port is None:
            raise RuntimeError("async front end failed to start")

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(asyncio.start_server(
                self._serve_connection, "0.0.0.0", self.requested_port,
                ssl=self.ssl_context, backlog=512))
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:  # noqa: BLE001 — surfaced to start()
            self._startup_error = e
            self._started.set()
            return
        watchdog = loop.create_task(self._watchdog())
        self._started.set()
        try:
            loop.run_forever()
        finally:
            try:
                watchdog.cancel()
                self._server.close()
                for w in list(self._writers):
                    try:
                        w.close()
                    except Exception:  # noqa: BLE001
                        pass
                loop.run_until_complete(asyncio.sleep(0))
            finally:
                loop.close()

    def shutdown(self) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(10.0)
        self._bridge.shutdown(wait=False)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- watchdog ------------------------------------------------------------

    async def _watchdog(self) -> None:
        """Measure loop lag: schedule a sleep, see how late it fires.
        A blocked loop (a handler doing synchronous work on it — the
        ``async-loop-block`` chaos) shows as lag past the stall
        threshold; count it and log the slow-request evidence."""
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.watchdog_interval)
            lag = loop.time() - t0 - self.watchdog_interval
            self.loop_lag_ms = max(0.0, lag * 1000.0)
            if lag > self.watchdog_stall:
                self.loop_stalls += 1
                metrics = self.app.metrics
                if metrics is not None:
                    metrics.inc("async_loop_stalls")
                _log.warning(
                    "SLOW LOOP: event loop blocked %.0f ms (threshold "
                    "%.0f ms) — a handler ran synchronous work on the "
                    "loop; open_connections=%d bridge_inflight=%d",
                    lag * 1000.0, self.watchdog_stall * 1000.0,
                    self.open_connections, self.bridge_inflight)

    # -- per-connection ------------------------------------------------------

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        if self.open_connections >= self.max_connections:
            # graceful at the cap: one fast 503, then close — a
            # refused client learns NOW instead of hanging in a
            # backlog the server will never drain
            self.rejected_connections += 1
            metrics = self.app.metrics
            if metrics is not None:
                metrics.inc("async_rejected_connections")
            try:
                writer.write(_error_response(
                    503, "connection limit reached", "", close=True))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            writer.close()
            return
        self.open_connections += 1
        self._writers.add(writer)
        try:
            while await self._one_request(reader, writer):
                pass
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        except asyncio.LimitOverrunError:
            pass
        finally:
            self.open_connections -= 1
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _one_request(self, reader, writer) -> bool:
        try:
            line = await reader.readline()
        except ValueError:  # overlong request line
            writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                         b"Content-Length: 0\r\n\r\n")
            await writer.drain()
            return False
        if line in (b"\r\n", b"\n"):  # tolerated leading blank line
            line = await reader.readline()
        if not line:
            return False  # clean keep-alive close
        parts = line.split()
        if len(parts) != 3 or not parts[2].startswith(b"HTTP/"):
            writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                         b"Content-Length: 0\r\n\r\n")
            await writer.drain()
            return False
        method = parts[0].decode("latin-1")
        target = parts[1].decode("latin-1")
        headers: dict[str, str] = {}
        while True:
            try:
                h = await reader.readline()
            except ValueError:
                h = b" " * 65537  # overlong header line: reject below
            if h in (b"\r\n", b"\n", b""):
                break
            # same guards as the threaded parser: bounded line/count,
            # reject missing ':' and obs-fold continuations (RFC 9112
            # §5 — request-smuggling surface)
            k, sep, v = h.partition(b":")
            if (len(h) > 65536 or len(headers) >= 128 or not sep
                    or h[:1] in (b" ", b"\t")):
                writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                             b"Content-Length: 0\r\n\r\n")
                await writer.drain()
                return False
            headers[k.decode("latin-1").strip().title()] = \
                v.decode("latin-1").strip()
        close = (headers.get("Connection", "").lower() == "close"
                 or parts[2] == b"HTTP/1.0")
        if headers.get("Transfer-Encoding"):
            # chunked framing is never negotiated here (same contract
            # as the threaded parser's _drain_body): the body is left
            # unread, so the connection must close or the chunk stream
            # would be parsed as the next request line — a response-
            # desync/smuggling surface behind a keep-alive proxy
            close = True
        if headers.get("Expect", "").lower() == "100-continue":
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()
        body = b""
        try:
            length = int(headers.get("Content-Length") or 0)
        except ValueError:
            length = 0  # the dispatcher 400s it; framing unknown
            close = True
        if length > 0:
            body = await reader.readexactly(length)
        # chaos: a handler that does synchronous work ON the loop —
        # the watchdog must see the stall and count it
        faults.fire("async-loop-block")

        if method in ("GET", "HEAD"):
            fast = await self._fast_path(method == "HEAD", target,
                                         headers)
            if fast is not None:
                writer.write(fast)
                await writer.drain()
                return not close
        # bridge: the full dispatcher on a bounded pool
        if self.bridge_inflight >= self.bridge_backlog:
            self.bridge_sheds += 1
            metrics = self.app.metrics
            if metrics is not None:
                metrics.inc("async_bridge_sheds")
            writer.write(_error_response(
                503, "overloaded; retry later",
                headers.get("Accept", ""), extra={"Retry-After": "1"}))
            await writer.drain()
            return not close
        self.bridge_inflight += 1
        try:
            payload, handler_close = await asyncio.get_running_loop() \
                .run_in_executor(self._bridge, self._dispatch, method,
                                 target, headers, body, close)
        finally:
            self.bridge_inflight -= 1
        writer.write(payload)
        await writer.drain()
        return not (close or handler_close)

    def _dispatch(self, method, target, headers, body,
                  close) -> tuple[bytes, bool]:
        """Bridge-pool worker: the FULL threaded dispatcher against a
        buffered handler — auth, admission, coalescing leadership,
        scatter, everything — producing the same wire bytes the
        threaded server would."""
        handler = _BufferedHandler(method, target, headers, body, close)
        try:
            if method in _KNOWN_METHODS:
                self.app.handle(handler)
            else:
                self.app._send_error(handler, 405, "method not allowed")
                self.app._drain_body(handler)
        except Exception as e:  # noqa: BLE001 — uniform 500, keep loop
            _log.exception("bridged dispatch failed")
            return _error_response(
                500, f"{type(e).__name__}: {e}",
                headers.get("Accept", ""), close=True), True
        return handler.wfile.getvalue(), handler._close

    # -- the on-loop fast path ----------------------------------------------

    def _deadline_sec(self, headers) -> float | None:
        """Remaining-budget seconds for an on-loop coalesce wait — the
        same tighter-of-two rule HttpApp._deadline applies."""
        ms = self.app.request_deadline_ms \
            if self.app.request_deadline_ms > 0 else None
        hdr = headers.get("X-Deadline-Ms")
        if hdr:
            try:
                client_ms = int(hdr)
            except ValueError:
                client_ms = None
            if client_ms is not None and client_ms >= 0:
                ms = client_ms if ms is None else min(ms, client_ms)
        return None if ms is None else ms / 1000.0

    async def _fast_path(self, head_only: bool, target: str,
                         headers: dict[str, str]) -> bytes | None:
        """Serve a cache hit (or join an in-flight leader) entirely on
        the loop; None = not servable here, bridge it.  DIGEST-secured
        routers always bridge: the challenge dance belongs to the full
        dispatcher."""
        app = self.app
        rc = app.result_cache
        if rc is None or app.user_name is not None:
            return None
        if not (rc.store_enabled or rc.coalesce):
            return None
        t0 = clockmod.monotonic()
        parsed = urllib.parse.urlparse(target)
        path = urllib.parse.unquote(parsed.path)
        if app.context_path and path.startswith(app.context_path):
            path = path[len(app.context_path):] or "/"
        route = match = None
        for r, regex in app._routes:
            if not r.cache or r.method != "GET":
                continue
            m = regex.match(path)
            if m is not None:
                route, match = r, m
                break
        if route is None:
            return None
        query = urllib.parse.parse_qs(parsed.query)
        probe = rc.probe(route.pattern, path, query, match.groupdict())
        if probe is None:
            return None
        entry = rc.lookup_present(probe)
        verdict = "hit"
        if entry is None and rc.coalesce:
            fl = rc.flight_for(probe.key)
            if fl is not None:
                entry = await self._join_flight(rc, fl, headers)
                verdict = "coalesced"
        if entry is None:
            return None
        if verdict == "coalesced":
            rc.count_coalesced()
            self.fast_coalesced += 1
        else:
            self.fast_hits += 1
        return self._render_response(route, entry, verdict, headers,
                                     head_only, t0)

    async def _join_flight(self, rc, flight, headers):
        """Park THIS COROUTINE on the leader's flight — the async form
        of the follower's event wait, costing a heap frame instead of
        a thread.  Returns the shared entry or None (leader died /
        uncacheable / timed out → the caller bridges to its own
        scatter, the can-save-work-never-lose-a-request contract)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def wake():
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))

        if rc.add_flight_waiter(flight, wake):
            timeout = rc.coalesce_wait_sec
            deadline = self._deadline_sec(headers)
            if deadline is not None:
                timeout = min(timeout, max(0.0, deadline))
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return None
        return flight.entry if flight.done else None

    def _render_response(self, route, entry, verdict, headers,
                         head_only, t0) -> bytes:
        """The wire form of lambda_rt.http._send_entry — same header
        order, same preserialized bytes, stamped ``X-Oryx-Cache`` —
        plus the request-side bookkeeping (metrics/trace/events) the
        threaded dispatcher would have done."""
        app = self.app
        accept = headers.get("Accept", "")
        span = None
        trace_id = None
        if app.tracer is not None:
            span = app.tracer.begin_request(
                app._request_span, headers.get("Traceparent"))
            if span.sampled:
                trace_id = span.trace_id
            with app.tracer.span("router.cache_lookup") as sp:
                sp.set_attr("cache", verdict)
        status = entry.status
        head = []
        if status != 200:
            # negative entry (hot 404): the same error page a cold
            # miss renders, Accept negotiation included
            payload, ctype = render_error_page(status, None,
                                               entry.value, accept)
            head.append(
                f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n")
            if trace_id:
                head.append(f"X-Oryx-Trace: {trace_id}\r\n")
            head.append(f"X-Oryx-Cache: {verdict}\r\n")
            head.append(f"Content-Type: {ctype}\r\n")
            head.append(f"Content-Length: {len(payload)}\r\n")
        else:
            gzip_ok = "gzip" in headers.get("Accept-Encoding", "")
            payload, ctype, gzipped = app.result_cache.render(
                entry, wants_csv(accept), gzip_ok, _render_kind)
            head.append("HTTP/1.1 200 OK\r\n")
            if trace_id:
                head.append(f"X-Oryx-Trace: {trace_id}\r\n")
            head.append(f"X-Oryx-Cache: {verdict}\r\n")
            head.append(f"Content-Type: {ctype}\r\n")
            if gzipped:
                head.append("Content-Encoding: gzip\r\n")
            head.append(f"Content-Length: {len(payload)}\r\n")
        head.append("\r\n")
        out = "".join(head).encode("latin-1")
        if not head_only:
            out += payload
        route_key = f"{route.method} {route.pattern}"
        dur = clockmod.monotonic() - t0
        if app.metrics is not None:
            app.metrics.record(route_key, status, dur,
                               trace_id=trace_id)
        if span is not None and span.sampled:
            app.tracer.end_request(span, status=status, route=route_key)
        if app.events is not None:
            dur_ms = dur * 1000.0
            if app.events.should_emit(status, dur_ms,
                                      trace_id is not None):
                spans = app.tracer.spans_for(trace_id) \
                    if app.tracer is not None and trace_id else None
                app.events.emit(route_key, status, dur_ms, trace_id,
                                spans)
        return out
