"""The scatter-gather gateway: the public serving front end, answered
by a horizontally-sharded replica fleet.

``python -m oryx_tpu router`` speaks the SAME public HTTP surface as a
single serving layer — endpoints, JSON/CSV negotiation, gzip, DIGEST
auth, HTTPS, ``X-Deadline-Ms`` — but holds no model: every item-scan
query scatters to the catalog shards discovered via update-topic
heartbeats (cluster/membership.py) and merges their exact local top-k
into the exact global top-N (cluster/merge.py).  The full user store
is replicated on every replica, so user-keyed lookups (known items,
most-active users) proxy to any live replica, and item-vector-keyed
math (estimates, similarity-to-item) gathers vectors from their owner
shards and computes at the gateway with the same host arithmetic the
single-node resources use.

Anonymous/context fold-in needs the full-catalog Gramian: the router
sums the shards' partial ``Y_s^T Y_s`` (``/shard/yty``, cached per
(shard, generation)) — row-disjoint slices sum to exactly the full
YtY — and runs the same ``ops.als_fold_in`` solve a replica would.

Degraded partial answers: when a shard is down or past deadline the
merge proceeds over the surviving shards, the response carries
``X-Oryx-Partial: shards=m/N``, and ``partial_answers`` counts on
``/metrics``.  When no shard survives: 503.  The router never
restarts over membership changes — kill/rejoin flows through the
registry (tests/test_cluster_it.py).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from typing import Sequence

import numpy as np

from ..common import clock as clockmod
from ..api.serving import OryxServingException
from ..common.config import Config
from ..kafka import utils as kafka_utils
from ..kafka.inproc import InProcTopicProducer, resolve_broker
from ..lambda_rt.http import HttpApp, Request, Route, TextResponse, \
    make_server
from ..lambda_rt.metrics import MetricsRegistry
from ..obs import (engine_from_config, events_from_config,
                   flight_from_config, merge_snapshots,
                   render_openmetrics_blocks,
                   render_prometheus_blocks, tracer_from_config)
from ..obs.server import (OPENMETRICS_CTYPE, admin_diagnose,
                          admin_flight, admin_flight_dump,
                          admin_profile, admin_region, admin_slo,
                          admin_tail, admin_traces,
                          own_prometheus_snapshot)
from ..ops import als_fold_in
from ..ops.solver import SingularMatrixSolverException, get_solver
from ..resilience import faults
from ..resilience.policy import (CircuitBreaker, ResilientTopicProducer,
                                 Retry, resilience_snapshot,
                                 run_with_resubscribe)
from ..serving import console
from ..serving.als import (IDCount, IDValue, how_many_offset,
                           parse_id_value_segments)
from ..serving.framework import send_input
from ..kafka.api import KEY_MODEL, KEY_MODEL_REF, KEY_UP
from .membership import KEY_HEARTBEAT, MembershipRegistry
from .merge import Row, merge_top_n
from .result_cache import ResultCache
from .scatter import ScatterGather, ShardResponse, ShardUnavailable
from .sharding import shard_of

_log = logging.getLogger(__name__)

__all__ = ["RouterLayer", "ROUTES"]


# -- request-scope helpers ----------------------------------------------------

def _reg(req: Request) -> MembershipRegistry:
    return req.context["membership"]


def _sg(req: Request) -> ScatterGather:
    return req.context["scatter"]


def _partial_headers(req: Request, failed: Sequence[int]) -> dict[str, str]:
    """The degraded-answer marker; also counts the event."""
    if not failed:
        return {}
    n = _reg(req).shard_count
    req.context["metrics"].inc("partial_answers")
    return {"X-Oryx-Partial": f"shards={n - len(failed)}/{n}"}


def _id_values(rows: Sequence[Row]) -> list[IDValue]:
    return [IDValue(i, float(s)) for i, s, _ in rows]


def _collect_rows(responses: dict[int, ShardResponse],
                  key: str = "rows"
                  ) -> tuple[list[list[Row]], int, list[int]]:
    """Row lists from the 2xx shard responses, the consensus non-2xx
    status (404 passthrough when every answering shard said 404), and
    the shards that answered non-2xx while OTHERS had rows — replay
    skew (e.g. one replica absorbed a new user's vector before its
    peer): their catalog slice is missing from the merge, which must
    surface as a partial answer, never as a silently incomplete 200."""
    rows, statuses, odd = [], [], []
    for shard, r in responses.items():
        if r.ok:
            rows.append([(str(i), float(s), int(o))
                         for i, s, o in (r.payload or {}).get(key) or []])
        else:
            statuses.append(r.status)
            odd.append(shard)
    miss = statuses[0] if statuses and not rows \
        and all(s == statuses[0] for s in statuses) else 0
    return rows, miss, (sorted(odd) if rows else [])


def _raise_for(miss: int, what: str) -> None:
    if miss:
        raise OryxServingException(
            miss, what if miss == 404 else f"shard error {miss}: {what}")


def _qs(pairs: list[tuple[str, str]]) -> str:
    return ("?" + urllib.parse.urlencode(pairs)) if pairs else ""


def _scatter_query(req: Request, body: dict,
                   deadline=None) -> tuple[dict[int, ShardResponse],
                                           list[int]]:
    payload = json.dumps(body).encode("utf-8")
    return _sg(req).scatter("POST", "/shard/query", payload,
                            deadline or req.deadline)


def _gather_vectors(req: Request, item_ids: Sequence[str] = (),
                    user_ids: Sequence[str] = ()
                    ) -> tuple[dict[str, np.ndarray | None],
                               dict[str, np.ndarray | None], list[int]]:
    """Fetch vectors: items from their owner shards, users from any
    replica.  Returns (item id -> vector|None, user id -> vector|None,
    failed owner shards).  Item and user vectors live in SEPARATE maps:
    X and Y are independent stores single-node, so one string may
    legitimately name both a user and an item."""
    sg, n = _sg(req), _reg(req).shard_count
    items_out: dict[str, np.ndarray | None] = {}
    users_out: dict[str, np.ndarray | None] = {}
    failed: list[int] = []
    by_owner: dict[int, list[str]] = {}
    for iid in item_ids:
        by_owner.setdefault(shard_of(iid, n), []).append(iid)
    for shard, ids in by_owner.items():
        body = json.dumps({"items": ids}).encode("utf-8")
        try:
            r = sg.query_shard(shard, "POST", "/shard/vectors", body,
                               req.deadline)
        except ShardUnavailable:
            failed.append(shard)
            for iid in ids:
                items_out.setdefault(iid, None)
            continue
        items = (r.payload or {}).get("items") or {}
        for iid in ids:
            v = items.get(iid)
            items_out[iid] = None if v is None else np.asarray(v, np.float32)
    if user_ids:
        body = json.dumps({"users": list(user_ids)}).encode("utf-8")
        r = sg.any_replica("POST", "/shard/vectors", body, req.deadline)
        users = (r.payload or {}).get("users") or {}
        for uid in user_ids:
            v = users.get(uid)
            users_out[uid] = None if v is None else np.asarray(v, np.float32)
    return items_out, users_out, failed


# -- cluster-wide Gramian (fold-in support) ----------------------------------

def _cluster_solver(req: Request) -> tuple[object, bool, int, list[int]]:
    """(solver over the summed cluster YtY, implicit flag, features,
    failed shards).  Partial Gramians are cached per
    (shard, generation) so a stable cluster pays one /shard/yty round
    per shard per model generation: the registry's heartbeats already
    carry each shard's live generation, so a cache hit for it costs no
    network at all — /shard/yty is only fetched for shards whose
    generation moved (or was never seen).  At f features the payload is
    f^2 floats (~0.5 MB of JSON at f=250); shipping that per fold-in
    request would dwarf the fold-in itself."""
    cache: dict = req.context["yty_cache"]
    lock = req.context["yty_lock"]
    reg, sg = _reg(req), _sg(req)
    n = reg.shard_count
    entries: dict[int, tuple] = {}
    missing: list[int] = []
    with lock:
        for shard in range(n):
            cands = reg.candidates(shard)
            entry = None
            if cands:
                # heartbeat generation of the replica a query would
                # hit.  Keyed by TOPOLOGY too: shard 0 of 2 and shard
                # 0 of 3 are different catalog slices, and a live
                # reshard must never reuse the old ring's partial
                # Gramian under the new ring's shard number
                entry = cache.get((n, shard, cands[0].generation))
            if entry is None:
                missing.append(shard)
            else:
                entries[shard] = entry
    failed: list[int] = []
    if missing:
        # the lock covers only the cache dict — fetches run outside it
        # (and concurrently), so one stalled shard cannot serialize
        # every fold-in request in the cluster behind its timeout
        try:
            responses, failed = sg.scatter("GET", "/shard/yty",
                                           deadline=req.deadline,
                                           shards=missing)
        except ShardUnavailable:
            responses, failed = {}, list(missing)
        with lock:
            for shard, r in sorted(responses.items()):
                if not r.ok or not r.payload:
                    failed.append(shard)
                    continue
                entry = (np.asarray(r.payload["yty"], dtype=np.float64),
                         bool(r.payload.get("implicit", True)),
                         int(r.payload.get("features", 0)))
                # one entry per (topology, shard): drop older
                # generations, and drop OTHER topologies wholesale — a
                # retired ring's partial Gramians are features² float64
                # blocks that would otherwise pin forever across
                # repeated reshards.  Keyed by the generation the
                # REPLICA reports (authoritative; a heartbeat mid-swap
                # may lag it by one — the next request re-checks
                # against the fresher heartbeat)
                for k in [k for k in cache
                          if k[0] != n or k[1] == shard]:
                    del cache[k]
                cache[(n, shard,
                       int(r.payload.get("generation", 0)))] = entry
                entries[shard] = entry
    total = None
    implicit, features = True, 0
    for shard in sorted(entries):
        mat, implicit, features = entries[shard]
        features = features or int(mat.shape[0])
        total = mat if total is None else total + mat
    if total is None:
        raise OryxServingException(503, "no shard Gramian available")
    try:
        solver = get_solver(total)
    except SingularMatrixSolverException as e:
        raise OryxServingException(
            503, "No solver available for model yet") from e
    return solver, implicit, features, sorted(set(failed))


def _fold_user_vector(req: Request, item_values: list[tuple[str, float]],
                      xu: np.ndarray | None
                      ) -> tuple[np.ndarray | None, int, list[int]]:
    """The gateway's EstimateForAnonymous.buildTemporaryUserVector:
    gather the context items' vectors from their owner shards, solve
    against the summed cluster Gramian, fold sequentially (the same
    ops.als_fold_in kernel a replica runs)."""
    solver, implicit, features, failed = _cluster_solver(req)
    vecs, _, failed_v = _gather_vectors(
        req, item_ids=[i for i, _ in item_values])
    xu = als_fold_in.fold_in_sequential(
        solver, list(item_values), lambda i: vecs.get(i), xu,
        implicit, features)
    return xu, features, sorted(set(failed) | set(failed_v))


# -- top-N family -------------------------------------------------------------

def _merged_response(req: Request, rows: list[list[Row]],
                     failed: Sequence[int], how_many: int, offset: int,
                     lowest: bool = False):
    tracer = req.context.get("tracer")
    if tracer is None:
        merged = merge_top_n(rows, how_many, offset, lowest=lowest)
    else:
        # the gather-side counterpart of the scatter's shard_call
        # spans: how long the exact cross-shard merge itself took
        with tracer.span("router.merge") as span:
            span.set_attr("shards_merged", len(rows))
            span.set_attr("rows_in", sum(len(r) for r in rows))
            merged = merge_top_n(rows, how_many, offset, lowest=lowest)
    return 200, _id_values(merged), _partial_headers(req, failed)


def _recommend(req: Request):
    how_many, offset = how_many_offset(req)
    k = how_many + offset
    pairs = [("howMany", str(k))]
    if req.q1("considerKnownItems"):
        pairs.append(("considerKnownItems", req.q1("considerKnownItems")))
    for p in req.q_list("rescorerParams"):
        pairs.append(("rescorerParams", p))
    path = ("/shard/recommend/"
            + urllib.parse.quote(req.params["userID"], safe="") + _qs(pairs))
    responses, failed = _sg(req).scatter("GET", path,
                                         deadline=req.deadline)
    rows, miss, odd = _collect_rows(responses)
    _raise_for(miss, req.params["userID"])
    return _merged_response(req, rows, sorted({*failed, *odd}),
                            how_many, offset)


def _recommend_to_many(req: Request):
    how_many, offset = how_many_offset(req)
    responses, failed = _scatter_query(req, {
        "kind": "recommendToMany",
        "userIDs": req.params["userIDs"].split("/"),
        "considerKnownItems":
            req.q1("considerKnownItems", "false") == "true",
        "howMany": how_many + offset,
        "rescorerParams": req.q_list("rescorerParams")})
    rows, miss, odd = _collect_rows(responses)
    _raise_for(miss, req.params["userIDs"])
    return _merged_response(req, rows, sorted({*failed, *odd}),
                            how_many, offset)


def _by_vector_scatter(req: Request, vectors, how_many: int,
                       exclude=(), cosine=False, lowest=False,
                       exclude_known_of=None, rescorer_hook=None,
                       rescorer_args=()):
    body = {"kind": "byVector",
            "vectors": [[float(x) for x in np.asarray(v, np.float32)]
                        for v in vectors],
            "howMany": how_many, "exclude": sorted(exclude),
            "cosine": cosine, "lowest": lowest}
    if exclude_known_of:
        body["excludeKnownOf"] = exclude_known_of
    if rescorer_hook:
        body["rescorerHook"] = rescorer_hook
        body["rescorerArgs"] = list(rescorer_args)
        body["rescorerParams"] = req.q_list("rescorerParams")
    return _scatter_query(req, body)


def _multi_rows(responses: dict[int, ShardResponse],
                index: int) -> list[list[Row]]:
    out = []
    for r in responses.values():
        if r.ok:
            multi = (r.payload or {}).get("multi") or []
            if index < len(multi):
                out.append([(str(i), float(s), int(o))
                            for i, s, o in multi[index]])
    return out


def _recommend_to_anonymous(req: Request):
    item_values = parse_id_value_segments(req.params["itemIDs"])
    how_many, offset = how_many_offset(req)
    xu, _, failed_fold = _fold_user_vector(req, item_values, None)
    if xu is None:
        raise OryxServingException(404, req.params["itemIDs"])
    known = sorted({i for i, _ in item_values})
    responses, failed = _by_vector_scatter(
        req, [xu], how_many + offset, exclude=known,
        rescorer_hook="get_recommend_to_anonymous_rescorer",
        rescorer_args=[known])
    rows = _multi_rows(responses, 0)
    return _merged_response(req, rows, sorted(set(failed) | set(failed_fold)),
                            how_many, offset)


def _recommend_with_context(req: Request):
    user_id = req.params["userID"]
    item_values = parse_id_value_segments(req.params["itemIDs"])
    how_many, offset = how_many_offset(req)
    _, users, _ = _gather_vectors(req, user_ids=[user_id])
    xu = users.get(user_id)
    if xu is None:
        raise OryxServingException(404, user_id)
    xu, _, failed_fold = _fold_user_vector(req, item_values, xu)
    responses, failed = _by_vector_scatter(
        req, [xu], how_many + offset,
        exclude={i for i, _ in item_values}, exclude_known_of=user_id,
        rescorer_hook="get_recommend_rescorer", rescorer_args=[user_id])
    rows = _multi_rows(responses, 0)
    return _merged_response(req, rows, sorted(set(failed) | set(failed_fold)),
                            how_many, offset)


# -- similarity family --------------------------------------------------------

def _similarity(req: Request):
    item_ids = req.params["itemIDs"].split("/")
    how_many, offset = how_many_offset(req)
    vecs, _, failed_own = _gather_vectors(req, item_ids=item_ids)
    for iid in item_ids:
        if vecs.get(iid) is None:
            if shard_of(iid, _reg(req).shard_count) in failed_own:
                raise OryxServingException(
                    503, f"shard owning {iid} unavailable")
            raise OryxServingException(404, iid)
    responses, failed = _by_vector_scatter(
        req, [vecs[i] for i in item_ids], how_many + offset,
        exclude=set(item_ids), cosine=True,
        rescorer_hook="get_most_similar_items_rescorer")
    rows = _multi_rows(responses, 0)
    return _merged_response(req, rows, failed, how_many, offset)


def _similarity_to_item(req: Request):
    to_item = req.params["toItemID"]
    item_ids = req.params["itemIDs"].split("/")
    vecs, _, failed_own = _gather_vectors(req, item_ids=[to_item] + item_ids)

    def _vec(iid):
        v = vecs.get(iid)
        if v is None:
            if shard_of(iid, _reg(req).shard_count) in failed_own:
                raise OryxServingException(
                    503, f"shard owning {iid} unavailable")
            raise OryxServingException(404, iid)
        return v

    to_vec = _vec(to_item)
    to_norm = float(np.linalg.norm(to_vec))
    out = []
    for iid in item_ids:
        v = _vec(iid)
        denom = to_norm * float(np.linalg.norm(v))
        out.append(IDValue(iid, float(np.dot(v, to_vec)) / denom
                           if denom > 0 else 0.0))
    return out


# -- estimates ----------------------------------------------------------------

def _estimate(req: Request):
    user_id = req.params["userID"]
    item_ids = req.params["itemIDs"].split("/")
    vecs, users, failed = _gather_vectors(req, item_ids=item_ids,
                                          user_ids=[user_id])
    xu = users.get(user_id)
    if xu is None:
        raise OryxServingException(404, user_id)
    out = []
    for iid in item_ids:
        yi = vecs.get(iid)
        out.append(IDValue(iid, 0.0 if yi is None
                           else float(xu @ yi)))
    # items owned by a dead shard estimate as 0.0 (the unknown-item
    # value) under the partial marker rather than failing the request
    return 200, out, _partial_headers(req, failed)


def _estimate_for_anonymous(req: Request):
    to_item = req.params["toItemID"]
    vecs, _, failed_own = _gather_vectors(req, item_ids=[to_item])
    to_vec = vecs.get(to_item)
    if to_vec is None:
        if shard_of(to_item, _reg(req).shard_count) in failed_own:
            raise OryxServingException(
                503, f"shard owning {to_item} unavailable")
        raise OryxServingException(404, to_item)
    item_values = parse_id_value_segments(req.params["itemIDs"])
    xu, _, failed = _fold_user_vector(req, item_values, None)
    value = 0.0 if xu is None else float(np.dot(xu, to_vec))
    return 200, value, _partial_headers(req, failed)


# -- known-items math ---------------------------------------------------------

def _because(req: Request):
    how_many, offset = how_many_offset(req)
    item_id = req.params["itemID"]
    vecs, _, failed_own = _gather_vectors(req, item_ids=[item_id])
    target = vecs.get(item_id)
    if target is None:
        if shard_of(item_id, _reg(req).shard_count) in failed_own:
            raise OryxServingException(
                503, f"shard owning {item_id} unavailable")
        raise OryxServingException(404, item_id)
    responses, failed = _scatter_query(req, {
        "kind": "because", "userID": req.params["userID"],
        "vector": [float(x) for x in target],
        "howMany": how_many + offset})
    rows, miss, odd = _collect_rows(responses)
    _raise_for(miss, req.params["userID"])
    return _merged_response(req, rows, sorted({*failed, *odd}),
                            how_many, offset)


def _most_surprising(req: Request):
    how_many, offset = how_many_offset(req)
    responses, failed = _scatter_query(req, {
        "kind": "mostSurprising", "userID": req.params["userID"],
        "howMany": how_many + offset})
    rows, miss, odd = _collect_rows(responses)
    _raise_for(miss, req.params["userID"])
    return _merged_response(req, rows, sorted({*failed, *odd}),
                            how_many, offset, lowest=True)


# -- proxied user-store endpoints --------------------------------------------

def _proxy_any(req: Request):
    """Forward to any live replica: these endpoints answer from the
    user store / known-items map, which every replica holds in full."""
    query = ""
    if req.query:
        query = "?" + urllib.parse.urlencode(
            [(k, v) for k, vs in req.query.items() for v in vs])
    # req.path arrives URL-DECODED from the front end: re-quote it for
    # the hand-rolled request line (an id with a space or non-latin-1
    # characters must round-trip the internal hop like any other)
    path = urllib.parse.quote(req.path, safe="/")
    try:
        r = _sg(req).any_replica("GET", path + query,
                                 deadline=req.deadline)
    except ShardUnavailable as e:
        raise OryxServingException(503, str(e)) from e
    if not r.ok:
        raise OryxServingException(r.status, str(r.payload))
    return r.payload


def _most_counts(req: Request):
    payload = _proxy_any(req)
    return [IDCount(str(d["id"]), int(d["count"])) for d in payload or []]


def _all_item_ids(req: Request):
    responses, failed = _scatter_query(req, {"kind": "allItemIDs"})
    seen, out = set(), []
    for _, r in sorted(responses.items()):
        if r.ok:
            for i in (r.payload or {}).get("ids") or []:
                if i not in seen:
                    seen.add(i)
                    out.append(i)
    return 200, out, _partial_headers(req, failed)


def _popular_representative_items(req: Request):
    try:
        meta = _sg(req).any_replica("GET", "/shard/meta",
                                    deadline=req.deadline)
    except ShardUnavailable as e:
        raise OryxServingException(503, str(e)) from e
    features = int((meta.payload or {}).get("features") or 0)
    if not features:
        raise OryxServingException(503, "Model not available yet")
    eye = np.eye(features, dtype=np.float32)
    responses, failed = _by_vector_scatter(req, list(eye), 1)
    items = []
    for i in range(features):
        top = merge_top_n(_multi_rows(responses, i), 1)
        items.append(top[0][0] if top else None)
    return 200, items, _partial_headers(req, failed)


# -- write path ---------------------------------------------------------------

def _gate_writes(req: Request) -> None:
    # parity with the single-node model gate: 503 while nothing could
    # serve the data back (no live replica at all)
    if not _reg(req).any_candidates():
        raise OryxServingException(503, "no live replica")


def _pref_post(req: Request):
    _gate_writes(req)
    body = req.body.decode().strip()
    value = body if body else "1"
    float(value)
    send_input(req, f"{req.params['userID']},{req.params['itemID']},{value}")
    return None


def _pref_delete(req: Request):
    _gate_writes(req)
    send_input(req, f"{req.params['userID']},{req.params['itemID']},")
    return None


def _ingest(req: Request):
    from ..serving.als import _ingest as serving_ingest
    _gate_writes(req)
    return serving_ingest(req)


# -- result-cache admin -------------------------------------------------------

def _cache(req: Request) -> "ResultCache":
    rc = req.context.get("result_cache")
    if rc is None:
        raise OryxServingException(
            404, "result cache disabled (oryx.cluster.cache.enabled / "
                 "oryx.cluster.coalesce.enabled)")
    return rc


def _cache_get(req: Request):
    """Operator stats for the exact result cache + coalescer: entry
    and byte occupancy, hit rate, invalidation/eviction/flush counts,
    in-flight coalesced scatters (docs/SCALING.md)."""
    return _cache(req).stats()


def _cache_flush(req: Request):
    """Drop every cached entry (the operator hatch — e.g. after
    arming a rescorer provider on the replicas, whose output the
    cache must not outlive)."""
    rc = _cache(req)
    return {"flushed": rc.flush("admin"), "stats": rc.stats()}


# -- topology admin -----------------------------------------------------------

def _topology_get(req: Request):
    """Reshard/topology status: the merged topology, the declared
    warming target's coverage and worst warm fraction, retired
    topologies, and the stale-heartbeat counter — the view the reshard
    runbook watches between 'start the M-way fleet' and 'cutover
    happened' (docs/SCALING.md)."""
    return _reg(req).topology_status()


def _topology_post(req: Request):
    """Declare a reshard target: ``{"of": M}``.  New-topology replicas'
    heartbeats are accepted from now on, and the router cuts over
    atomically once every one of the M shards has a live ready
    replica.  Declaring a retired topology un-retires it (scale back
    down); declaring the merged topology cancels a pending target."""
    try:
        body = json.loads(req.body.decode("utf-8"))
        of = int(body["of"])
    except (ValueError, TypeError, KeyError) as e:
        raise OryxServingException(
            400, f'body must be {{"of": M}}: {e}') from e
    try:
        return _reg(req).begin_reshard(of)
    except ValueError as e:
        raise OryxServingException(400, str(e)) from e


# -- framework ----------------------------------------------------------------

def _ready(req: Request):
    """200 when every catalog shard has a live ready replica."""
    reg = _reg(req)
    covered = reg.covered_shards()
    if len(covered) < reg.shard_count or reg.shard_count < 1:
        raise OryxServingException(
            503, f"shards covered: {len(covered)}/{reg.shard_count}")
    return None


def _prometheus_metrics(req: Request, registry: MetricsRegistry,
                        fmt: str):
    """The router's non-JSON /metrics forms.  ``prometheus-json`` is
    the router's OWN mergeable snapshot; ``prometheus`` and
    ``openmetrics`` additionally scrape every live replica's snapshot
    and render the cluster-wide merge — fixed-bucket histogram counts
    sum exactly across replicas (obs/prom.py), which reservoir
    percentiles never could.  The OpenMetrics form carries each
    bucket's exemplar through the merge (newest per bucket wins), so a
    cluster-wide p99 bucket still names one concrete trace."""
    snap = own_prometheus_snapshot(req, registry)
    if fmt == "prometheus-json":
        return snap
    scraped = _sg(req).scrape_replicas(
        "/metrics?format=prometheus-json", deadline=req.deadline)
    merged = merge_snapshots([payload for _, payload in scraped])
    # how many replicas the merged block actually covers: a replica
    # that failed its scrape is silently absent from the sums, and the
    # reader must be able to tell a full view from a partial one
    merged["gauges"] = {"scraped_replicas": len(scraped)}
    # one exposition for both blocks: the text format allows exactly
    # one # TYPE line per metric name, so the families are emitted
    # once with router- and replica-labeled samples grouped together
    blocks = [(snap, {"tier": "router"}), (merged, {"tier": "replica"})]
    if fmt == "openmetrics":
        return TextResponse(render_openmetrics_blocks(blocks),
                            content_type=OPENMETRICS_CTYPE)
    return TextResponse(render_prometheus_blocks(blocks))


def _metrics(req: Request):
    registry: MetricsRegistry = req.context["metrics"]
    fmt = req.q1("format", "json")
    if fmt in ("prometheus", "prometheus-json", "openmetrics"):
        return _prometheus_metrics(req, registry, fmt)
    out = {
        "routes": registry.snapshot(),
        "counters": registry.counters_snapshot(),
        "cluster": {
            "membership": _reg(req).snapshot(),
            "scatter": _sg(req).stats(),
            "covered_shards": _reg(req).covered_shards(),
        },
        "resilience": resilience_snapshot(),
    }
    admission = req.context.get("admission")
    if admission is not None:
        out["cluster"]["admission"] = admission.stats()
    ingest_gate = req.context.get("ingest_gate")
    if ingest_gate is not None:
        out["cluster"]["ingest"] = ingest_gate.stats()
    result_cache = req.context.get("result_cache")
    if result_cache is not None:
        out["cluster"]["cache"] = result_cache.stats()
    gauges = registry.gauges_snapshot()
    if gauges:
        out["freshness"] = gauges
    tracer = req.context.get("tracer")
    if tracer is not None:
        out["obs"] = {"trace_record_failures": tracer.record_failures}
    return out


def _error(req: Request):
    from ..serving.framework import _error as framework_error
    return framework_error(req)


ROUTES = [
    # admission=True marks the scatter data plane: when the admission
    # controller measures overload these shed as fast 503 + Retry-After
    # (cluster/admission.py); health/admin/write endpoints stay open.
    # cache=True marks the exact-result-cache surface (routes whose
    # answers have a precise per-user/per-item invalidation key —
    # cluster/result_cache.py); a hit bypasses the admission gate.
    Route("GET", "/recommend/{userID}", _recommend, admission=True,
          cache=True),
    Route("GET", "/recommendToMany/{userIDs:+}", _recommend_to_many,
          admission=True, cache=True),
    Route("GET", "/recommendToAnonymous/{itemIDs:+}",
          _recommend_to_anonymous, admission=True, cache=True),
    Route("GET", "/recommendWithContext/{userID}/{itemIDs:+}",
          _recommend_with_context, admission=True, cache=True),
    Route("GET", "/similarity/{itemIDs:+}", _similarity, admission=True,
          cache=True),
    Route("GET", "/similarityToItem/{toItemID}/{itemIDs:+}",
          _similarity_to_item, admission=True, cache=True),
    Route("GET", "/estimate/{userID}/{itemIDs:+}", _estimate,
          admission=True, cache=True),
    Route("GET", "/estimateForAnonymous/{toItemID}/{itemIDs:+}",
          _estimate_for_anonymous, admission=True, cache=True),
    Route("GET", "/because/{userID}/{itemID}", _because, admission=True,
          cache=True),
    Route("GET", "/mostSurprising/{userID}", _most_surprising,
          admission=True, cache=True),
    Route("GET", "/mostActiveUsers", _most_counts, admission=True),
    Route("GET", "/mostPopularItems", _most_counts, admission=True),
    Route("GET", "/popularRepresentativeItems",
          _popular_representative_items, admission=True),
    Route("GET", "/user/allIDs", _proxy_any, admission=True),
    Route("GET", "/allUserIDs", _proxy_any, admission=True),
    Route("GET", "/item/allIDs", _all_item_ids, admission=True),
    Route("GET", "/allItemIDs", _all_item_ids, admission=True),
    Route("GET", "/knownItems/{userID}", _proxy_any, admission=True,
          cache=True),
    Route("POST", "/pref/{userID}/{itemID}", _pref_post, mutates=True),
    Route("DELETE", "/pref/{userID}/{itemID}", _pref_delete, mutates=True),
    Route("POST", "/ingest", _ingest, mutates=True),
    Route("GET", "/ready", _ready),
    Route("GET", "/metrics", _metrics),
    # ?join=1 merges every live replica's ring by trace id — the
    # cluster-complete view /admin/tail consumes by default
    Route("GET", "/admin/traces", admin_traces),
    Route("GET", "/admin/tail", admin_tail),
    Route("GET", "/admin/slo", admin_slo),
    # mutating: captures device state to disk — read-only mode and
    # DIGEST auth (when configured) both gate it
    Route("GET", "/admin/profile", admin_profile, mutates=True),
    # region identity: which active-active region answered — the
    # failover runbook's first probe (docs/SCALING.md "Multi-region")
    Route("GET", "/admin/region", admin_region),
    # flight recorder + cluster auto-triage (obs/flight.py,
    # obs/diagnose.py); /admin/flight 404s until the config gate opens,
    # /admin/diagnose joins every live replica's surface via ?join=1
    Route("GET", "/admin/flight", admin_flight),
    Route("GET", "/admin/diagnose", admin_diagnose),
    # mutating: writes a bundle to the store AND fans the dump
    # cluster-wide when the trigger originates here
    Route("POST", "/admin/flight/dump", admin_flight_dump,
          mutates=True),
    # elastic-topology admin: reshard status + target declaration
    Route("GET", "/admin/topology", _topology_get),
    Route("POST", "/admin/topology", _topology_post, mutates=True),
    # result-cache admin: occupancy/hit-rate stats + the flush hatch
    Route("GET", "/admin/cache", _cache_get),
    Route("POST", "/admin/cache/flush", _cache_flush, mutates=True),
    Route("GET", "/error", _error),
    console.console_route("ALS scatter-gather gateway", [
        console.Endpoint("/recommend/{0}", ("userID",)),
        console.Endpoint("/similarity/{0}/{1}", ("itemID1", "itemID2")),
        console.Endpoint("/estimate/{0}/{1}", ("userID", "itemID")),
        console.Endpoint("/mostPopularItems"),
        console.Endpoint("/allUserIDs"),
        console.Endpoint("/metrics"),
        console.Endpoint("/ready"),
    ]),
]


class RouterLayer:
    """start()/await_()/close() around the gateway HTTP server and the
    membership consumer — the same lifecycle contract as the other
    layers, so ``python -m oryx_tpu router`` runs supervised like the
    rest."""

    def __init__(self, config: Config, port: int | None = None):
        self.config = config
        api = "oryx.serving.api"
        self.keystore_file = config.get_optional_string(f"{api}.keystore-file")
        self.keystore_password = config.get_optional_string(
            f"{api}.keystore-password")
        if port is not None:
            self.port = port
        elif self.keystore_file:
            self.port = config.get_int(f"{api}.secure-port")
        else:
            self.port = config.get_int(f"{api}.port")
        self.read_only = config.get_bool(f"{api}.read-only")
        self.update_broker = config.get_optional_string(
            "oryx.update-topic.broker")
        self.update_topic = config.get_optional_string(
            "oryx.update-topic.message.topic")
        self.input_broker = config.get_optional_string(
            "oryx.input-topic.broker")
        self.input_topic = config.get_optional_string(
            "oryx.input-topic.message.topic")
        if not (self.update_broker and self.update_topic):
            raise ValueError("router requires an update topic for "
                             "replica membership")
        faults.configure_from_config(config)
        ttl = config.get_int("oryx.cluster.heartbeat-ttl-ms") / 1000.0
        # region-pinned membership (multi-region serving): a foreign
        # region's heartbeats on this topic — a mirror misconfiguration
        # — are rejected, never routed (docs/SCALING.md "Multi-region")
        self.region = config.get_optional_string(
            "oryx.cluster.region.name")
        self.membership = MembershipRegistry(ttl, region=self.region)
        # sampled distributed tracing (obs/trace.py; None = disabled):
        # the request span opens at the HTTP dispatcher, each shard
        # query runs under a router.shard_call span whose context rides
        # the internal hop as the `traceparent` header
        self.tracer = tracer_from_config(config, "router")
        self.scatter = ScatterGather(self.membership, config,
                                     tracer=self.tracer)
        self.metrics = MetricsRegistry()
        # measured-queue-wait admission control (cluster/admission.py;
        # both gates default 0 = off — the shipped router admits all)
        from .admission import AdmissionController
        self.admission = AdmissionController(config, self.scatter,
                                             self.metrics)
        # the admission signal, visible as a freshness-style gauge so
        # the autoscaler and operators read the same number the gate
        # uses
        self.metrics.gauge_fn("cluster_queue_wait_ms",
                              self.scatter.cluster_queue_wait_ms)
        # exact result cache + single-flight coalescing on the scatter
        # hot path (cluster/result_cache.py; None = both gates off).
        # Invalidated precisely from the SAME update-topic tap the
        # membership consumer runs — no extra consumer, no TTLs.
        self.result_cache = ResultCache.from_config(
            config, self.metrics, self.membership)
        # SLO burn-rate engine over the router's own exactly-mergeable
        # bucket counters (obs/slo.py; None = disabled).  Evaluated
        # lazily on gauge reads, alert state at /admin/slo, and the
        # burn gauge is the autoscaler's SLO pressure signal.
        self.slo_engine = engine_from_config(config, self.metrics)
        if self.slo_engine is not None:
            self.metrics.gauge_fn("slo_burn_rate",
                                  self.slo_engine.burn_gauge)
            self.metrics.gauge_fn("slo_error_budget_remaining",
                                  self.slo_engine.budget_gauge)
        # wide-event request log (obs/events.py; None = disabled)
        self.events = events_from_config(config, "router", self.metrics)
        if self.events is not None:
            reg = self.metrics

            def _event_context() -> dict:
                # schema catch-up (PR 19): requests that served while
                # the write path was shedding carry the cumulative count
                n = int(reg.counters_snapshot().get("ingest_sheds", 0))
                return {"ingest_sheds": n} if n else {}

            self.events.context_fn = _event_context
        # flight recorder (obs/flight.py; None until the config gate
        # opens).  The router is the trigger fan-out root: its dump's
        # trigger id rides a POST to every live ready replica over the
        # scatter transport, so one page yields one correlated bundle
        # per live process.
        self.flight = flight_from_config(config, "router", self.metrics,
                                         slo=self.slo_engine)
        if self.flight is not None:
            flight = self.flight
            sg = self.scatter
            flight.fan_out = lambda tid, reason: len(sg.scrape_replicas(
                f"/admin/flight/dump?trigger={tid}&reason={reason}",
                method="POST"))
            if self.slo_engine is not None:
                # page transition -> one debounced cluster-wide dump;
                # the callback runs with the SLO lock held and
                # trigger() never re-enters the engine
                self.slo_engine.on_page = \
                    lambda name, st: flight.trigger(
                        "slo-page", {"objective": name,
                                     "burn_5m": st.get("burn_5m")})
        self.input_producer = None
        self.input_breaker = CircuitBreaker.from_config(
            "router-input", config)
        if not self.read_only and self.input_broker and self.input_topic:
            if not config.get_bool("oryx.serving.no-init-topics"):
                kafka_utils.maybe_create_topic(
                    self.input_broker, self.input_topic,
                    partitions=kafka_utils.input_topic_partitions(config))
            self.input_producer = ResilientTopicProducer(
                InProcTopicProducer(self.input_broker, self.input_topic),
                retry=Retry.from_config("router-input-send", config),
                breaker=self.input_breaker)
        # write-path admission (serving/ingest.py), the scatter
        # AdmissionController's twin: bounded in-flight input-topic
        # appends + measured-send-lag shedding around the /ingest and
        # /pref produce only — fast 503 + Retry-After + ingest_sheds,
        # health/admin/read routes never gated
        from ..serving.ingest import IngestGate
        self.ingest_gate = IngestGate(config, self.metrics)
        if not self.ingest_gate.enabled:
            self.ingest_gate = None
        self._stop = threading.Event()
        self._consume_thread: threading.Thread | None = None
        self._server = None
        self._server_thread: threading.Thread | None = None
        # C10K front end (cluster/async_http.py): an asyncio event
        # loop replaces thread-per-connection when enabled — cache
        # hits and coalesced followers never leave the loop, misses
        # bridge to a fixed worker pool, and concurrency is bounded by
        # file descriptors instead of thread stacks
        self.async_enabled = config.get_bool(
            "oryx.cluster.async.enabled")
        self._frontend = None
        self.app = HttpApp(
            ROUTES,
            context={
                "membership": self.membership,
                "scatter": self.scatter,
                "metrics": self.metrics,
                "tracer": self.tracer,
                "config": config,
                "input_producer": self.input_producer,
                "ingest_gate": self.ingest_gate,
                "admission":
                    self.admission if self.admission.enabled else None,
                "result_cache": self.result_cache,
                "slo": self.slo_engine,
                "events": self.events,
                "flight": self.flight,
                "yty_cache": {},
                "yty_lock": threading.Lock(),
                # /admin/region enrichment: the router's region answers
                # with its routed topology + epoch so a failover
                # runbook reads identity AND health in one probe
                "region_info": self._region_info,
            },
            read_only=self.read_only,
            user_name=config.get_optional_string(f"{api}.user-name"),
            password=config.get_optional_string(f"{api}.password"),
            context_path=config.get_string(f"{api}.context-path"),
            request_deadline_ms=config.get_int(
                "oryx.resilience.request-deadline-ms"),
        )

    def _region_info(self) -> dict:
        """The router's /admin/region block: identity + the local
        fleet's routed topology and cache epoch, so re-pointed clients
        can verify both WHERE they landed and that the region can
        serve (the failover runbook's one probe)."""
        of, gens, mixed = self.membership.generation_topology()
        return {
            "role": "router",
            "merged_of": of,
            "covered_shards": self.membership.covered_shards(),
            "generation_epoch": list(gens),
            "epoch_mixed": mixed,
        }

    # -- lifecycle -----------------------------------------------------------

    def _consume_membership(self) -> None:
        broker = resolve_broker(self.update_broker)
        rc = self.result_cache
        cutovers_seen = self.membership.topology_cutovers

        tailed_before = [False]

        def tail():
            nonlocal cutovers_seen
            # from the CURRENT end: membership is periodic state, not
            # history — replicas re-announce every interval, so the
            # registry is complete one heartbeat period after start.
            # The CACHE's invalidations are one-shot, though: a
            # resubscribe after a consumer failure skips whatever UP
            # records went by during the gap, so the restarted tail
            # flushes the epoch — heartbeats self-heal, evictions
            # don't.
            if tailed_before[0] and rc is not None:
                rc.flush("tap-resubscribe")
            tailed_before[0] = True
            for km in broker.consume(self.update_topic,
                                     from_beginning=False,
                                     stop=self._stop):
                if km.key == KEY_HEARTBEAT:
                    if not self.membership.note_message(km.message):
                        # dropped: retired fleet still announcing, or a
                        # misconfigured i/N replica whose ring does not
                        # exist here — countable evidence, never merged
                        self.metrics.inc("stale_topology_heartbeats")
                    if rc is not None:
                        # a topology cutover retires a whole ring: its
                        # entries can never be served (the topology is
                        # in every key) — reclaim their bytes now
                        cut = self.membership.topology_cutovers
                        if cut != cutovers_seen:
                            cutovers_seen = cut
                            rc.flush("topology-cutover")
                elif rc is not None:
                    # the result cache's invalidation feed rides the
                    # SAME tap: UP records evict exactly the touched
                    # user's/item's keys, a model publish flushes the
                    # epoch (the stale-feed safety valve)
                    if km.key == KEY_UP:
                        rc.note_up(km.message)
                    elif km.key in (KEY_MODEL, KEY_MODEL_REF):
                        rc.note_generation_publish()

        run_with_resubscribe(tail, stop=self._stop,
                             what="router membership consumer", log=_log)

    def start(self) -> None:
        self._consume_thread = threading.Thread(
            target=self._consume_membership, daemon=True,
            name="RouterMembership")
        self._consume_thread.start()
        ssl_context = None
        if self.keystore_file:
            import ssl
            ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_context.load_cert_chain(self.keystore_file,
                                        password=self.keystore_password)
        self.scheme = "https" if ssl_context is not None else "http"
        if self.async_enabled:
            from .async_http import AsyncFrontEnd
            self._frontend = AsyncFrontEnd(self.app, self.port,
                                           self.config,
                                           ssl_context=ssl_context)
            self._frontend.start()
            self.port = self._frontend.port
            fe = self._frontend
            self.metrics.gauge_fn(
                "async_open_connections",
                lambda: float(fe.open_connections))
            self.metrics.gauge_fn("async_loop_lag_ms",
                                  lambda: float(fe.loop_lag_ms))
            _log.info("Router (async front end) listening on port %d",
                      self.port)
        else:
            self._server = make_server(self.app, self.port,
                                       ssl_context=ssl_context)
            self.port = self._server.server_address[1]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="RouterHTTP")
            self._server_thread.start()
            _log.info("Router listening on port %d", self.port)
        if self.scatter.transport is not None:
            sg = self.scatter
            self.metrics.gauge_fn(
                "transport_open_connections",
                lambda: float(sg.transport.open_connections()))

    def await_(self) -> None:
        if self._frontend is not None:
            while self._frontend.is_alive():
                clockmod.sleep(1.0)
            return
        while self._server_thread and self._server_thread.is_alive():
            self._server_thread.join(1.0)

    def close(self) -> None:
        self._stop.set()
        if self._frontend is not None:
            self._frontend.shutdown()
        if self._server:
            self._server.shutdown()
        self.scatter.close()
        if self.flight is not None:
            self.flight.close()
        if self.events is not None:
            self.events.close()
        if self.input_producer:
            self.input_producer.close()
        for t in (self._consume_thread, self._server_thread):
            if t:
                t.join(10.0)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
