"""Replica membership: heartbeats on the update topic + the router's
live registry, including the elastic-topology state machine.

Replicas publish small JSON heartbeats under the ``HB`` key on the
same update topic that carries MODEL/MODEL-REF/UP — no extra
infrastructure, and the router discovers replicas by tailing the topic
it already understands.  Every update-topic consumer that is not the
router must skip ``HB`` records (:func:`without_heartbeats`); they are
control-plane traffic, not model state.

A heartbeat carries the replica's shard assignment, its public URL,
the model *generation* it is currently serving (count of accepted
MODEL/MODEL-REF documents since replay offset 0 — identical across
replicas because the update topic is totally ordered), and a ``ready``
flag (fraction loaded past the serving gate).  The registry routes
only to ready replicas and, within a shard, prefers the newest
generation — a replica still replaying an older model is never routed.

Liveness is judged by *receive* time (router monotonic clock), not the
sender's timestamp, so clock skew between hosts cannot fake liveness.

Topology state machine (live N→M resharding)
--------------------------------------------

Exactness requires merging replicas of ONE topology only — a ``0/1``
replica's catalog overlaps an ``i/2`` shard's, so mixing ``of`` values
in a merge would duplicate items.  The registry therefore routes one
*merged* topology at a time and moves between topologies through an
explicit lifecycle:

- **bootstrap** — nothing merged yet: the first topology to reach full
  ready coverage (every shard with a live ready replica) is committed;
  until one does, routing provisionally follows the largest ``of``
  announced (partial answers during cluster bring-up, exactly the old
  behavior).
- **warming** — with a topology merged, a *declared* reshard target
  (:meth:`MembershipRegistry.begin_reshard`, the router's
  ``POST /admin/topology``) may announce ``(shard, of=M)`` heartbeats;
  its replicas replay the update topic filtered through the murmur2
  ring and are tracked but never routed.
- **cutover** — the moment the target reaches full ready coverage the
  registry atomically (under its one lock) retires the old topology
  and routes the new one.  Nothing in between: a request routes either
  entirely old or entirely new.
- **retired** — the old fleet's continuing heartbeats are dropped and
  counted (``stale_topology_heartbeats``), and its registry entries
  are purged at cutover, so a retired replica can never be merged
  again.  Re-declaring a retired ``of`` (scale back down) un-retires
  it.

A heartbeat whose ``of`` is neither the merged topology nor the
declared target is **rejected** with the same counter — a misconfigured
``i/N`` replica cannot be merged into the wrong ring, and a lone
``0/1`` replica (trivially "fully covered" by itself) cannot yank the
routed topology.  One recovery hatch: when the merged topology has had
no live replica for several TTLs (``REBOOTSTRAP_GRACE_TTLS`` — the
fleet is dead, not blinking through a broker stall or GC pause; the
old stop-the-world reshard, or a total outage), the registry re-enters
bootstrap acceptance so a fresh fleet of any non-retired topology can
take over without an admin call.
"""

from __future__ import annotations

import json
import logging
import threading
import uuid
from dataclasses import dataclass
from typing import Iterable, Iterator

from ..common import clock as clockmod
from ..kafka.api import KeyMessage
from ..resilience import faults

_log = logging.getLogger(__name__)

__all__ = ["KEY_HEARTBEAT", "Heartbeat", "MembershipRegistry",
           "HeartbeatPublisher", "without_heartbeats"]

# update-topic key for replica heartbeats (rides next to MODEL/UP;
# consumers that build model state skip it)
KEY_HEARTBEAT = "HB"


def without_heartbeats(updates: Iterable[KeyMessage]) -> Iterator[KeyMessage]:
    """Drop cluster heartbeats from an update-topic stream — the filter
    every model-state consumer (serving/speed) tails through."""
    return (km for km in updates if km.key != KEY_HEARTBEAT)


@dataclass
class Heartbeat:
    replica: str          # stable per-process id
    shard: int            # catalog shard this replica serves
    of: int               # total shard count the replica was started with
    url: str              # public base URL, e.g. http://10.0.0.3:8080
    generation: int       # accepted MODEL documents since replay offset 0
    ready: bool           # fraction loaded past the serving gate
    fraction: float = 0.0
    ts: float = 0.0       # sender wall clock (diagnostic only)
    # region identity (oryx.cluster.region.name; None = unset): a
    # multi-region deployment's defense in depth — the mirror already
    # drops HB records at the link (cluster/mirror.py), but a
    # misconfigured shared topic must still never route a router at
    # replicas it cannot reach across the region boundary
    region: str | None = None
    # framed-transport listener port (cluster/transport.py; None =
    # the replica speaks only HTTP/1.1 internally).  The router falls
    # back to the HTTP hop per replica, so a mixed fleet mid-rollout
    # keeps serving
    tport: int | None = None

    def to_json(self) -> str:
        d = {k: v for k, v in self.__dict__.items()
             if not (k in ("region", "tport") and v is None)}
        return json.dumps(d, separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "Heartbeat | None":
        try:
            d = json.loads(s)
            region = d.get("region")
            tport = d.get("tport")
            return cls(replica=str(d["replica"]), shard=int(d["shard"]),
                       of=int(d["of"]), url=str(d["url"]),
                       generation=int(d["generation"]),
                       ready=bool(d["ready"]),
                       fraction=float(d.get("fraction", 0.0)),
                       ts=float(d.get("ts", 0.0)),
                       region=None if region is None else str(region),
                       tport=None if tport is None else int(tport))
        except (ValueError, TypeError, KeyError):
            return None  # malformed control message: ignore, don't die


class MembershipRegistry:
    """Router-side view of the cluster, built from heartbeats.

    ``candidates(shard)`` returns the ready replicas of a shard in the
    merged topology — an R-way replica *group* when several replicas
    announce the same ``(shard, of)`` — newest generation first, ties
    rotated round-robin for load spreading.  ``shard_count`` is the
    merged topology (see the module docstring's state machine), so the
    router needs no shard-count config of its own and reports partial
    answers as ``m/N`` against the true topology.
    """

    def __init__(self, ttl_sec: float, clock=clockmod.monotonic,
                 region: str | None = None):
        self.ttl_sec = ttl_sec
        self._clock = clock
        # this router's region (oryx.cluster.region.name).  With a
        # region set, a heartbeat stamped with a DIFFERENT region is
        # rejected like a stale topology: the replica is in another
        # region's fleet and routing to it would cross the region
        # boundary the mirror exists to avoid.  Unstamped heartbeats
        # (single-region deployments, older replicas) always merge.
        self.region = region
        self._lock = threading.Lock()
        # replica id -> (Heartbeat, last_seen_monotonic)
        self._replicas: dict[str, tuple[Heartbeat, float]] = {}
        self._of = 0                    # largest of ever seen (bootstrap)
        self._merged_of = 0             # committed routed topology; 0 = none
        self._target_of: int | None = None  # declared reshard target
        self._retired: set[int] = set()
        self._rr = 0
        self.heartbeats_seen = 0
        # heartbeats dropped because their `of` is neither the merged
        # topology nor the declared warming target (misconfigured
        # replicas, retired fleets still announcing)
        self.stale_topology_heartbeats = 0
        self.topology_cutovers = 0
        # when a merged-topology heartbeat was last received: gates the
        # re-bootstrap hatch (see _merged_grace_expired_locked)
        self._merged_last_live: float | None = None

    # A transient heartbeat gap (broker stall, GC/VM pause) must not
    # open the bootstrap hatch: a foreign topology can take over only
    # after the merged fleet has been silent this many TTLs — long
    # enough that it is dead, not blinking.  A one-TTL blip with a
    # misconfigured 0/1 replica beating would otherwise commit the
    # rogue ring and permanently retire the real fleet.
    REBOOTSTRAP_GRACE_TTLS = 3.0

    def _merged_grace_expired_locked(self, now: float) -> bool:
        if self._merged_last_live is None:
            return True
        return (now - self._merged_last_live
                > self.ttl_sec * self.REBOOTSTRAP_GRACE_TTLS)

    # -- topology lifecycle ---------------------------------------------------

    def begin_reshard(self, of: int) -> dict:
        """Declare ``of`` as the warming reshard target: its replicas'
        heartbeats are accepted (and tracked on /admin/topology) and
        the registry cuts over to it the moment every one of its shards
        has a live ready replica.  Re-declaring a retired topology
        un-retires it (scale back down).  Declaring the merged topology
        cancels any pending target."""
        if of < 1:
            raise ValueError(f"shard count must be >= 1, got {of}")
        with self._lock:
            if of == self._merged_of:
                self._target_of = None
            else:
                self._retired.discard(of)
                self._target_of = of
            return self._status_locked()

    def _merged_live_locked(self, now: float) -> bool:
        return any(hb.of == self._merged_of
                   and now - seen <= self.ttl_sec
                   for hb, seen in self._replicas.values())

    def note(self, hb: Heartbeat) -> bool:
        """Absorb one heartbeat; False = dropped as stale/misconfigured
        (counted in ``stale_topology_heartbeats``, entry purged)."""
        with self._lock:
            self.heartbeats_seen += 1
            now = self._clock()
            if hb.of < 1 or not 0 <= hb.shard < hb.of:
                # structurally invalid shard coordinates: never routable
                self.stale_topology_heartbeats += 1
                self._replicas.pop(hb.replica, None)
                return False
            if (self.region is not None and hb.region is not None
                    and hb.region != self.region):
                # a foreign region's replica on this topic (mirror
                # misconfiguration, shared broker): countable evidence,
                # never merged — its URL is across the region boundary
                self.stale_topology_heartbeats += 1
                self._replicas.pop(hb.replica, None)
                return False
            if hb.of in self._retired:
                # a retired fleet still announcing after cutover: aged
                # out instantly, counted, never merged
                self.stale_topology_heartbeats += 1
                self._replicas.pop(hb.replica, None)
                return False
            if (self._merged_of
                    and hb.of not in (self._merged_of, self._target_of)
                    and (self._merged_live_locked(now)
                         or not self._merged_grace_expired_locked(now))):
                # a foreign topology that is neither merged nor the
                # declared warming target, while the merged fleet is
                # alive (or only blinking, within the grace window): a
                # misconfigured i/N replica must not be merged into the
                # wrong ring.  Once the merged fleet has been silent
                # past the grace the cluster re-enters bootstrap
                # acceptance — a fresh fleet may take over without an
                # admin call.
                self.stale_topology_heartbeats += 1
                self._replicas.pop(hb.replica, None)
                return False
            self._replicas[hb.replica] = (hb, now)
            if hb.of == self._merged_of:
                self._merged_last_live = now
            if hb.of > self._of:
                self._of = hb.of
            return True

    def note_message(self, message: str) -> bool:
        hb = Heartbeat.from_json(message)
        if hb is not None:
            return self.note(hb)
        _log.warning("Malformed heartbeat ignored")
        return True  # malformed, not stale: not the rejection counter

    @property
    def shard_count(self) -> int:
        with self._lock:
            return self._topology_locked()

    def _live_locked(self) -> list[Heartbeat]:
        now = self._clock()
        return [hb for hb, seen in self._replicas.values()
                if now - seen <= self.ttl_sec]

    def _full_coverage_locked(self) -> list[int]:
        """Topologies whose EVERY shard has a live ready replica."""
        cov: dict[int, set[int]] = {}
        for hb in self._live_locked():
            if hb.ready:
                cov.setdefault(hb.of, set()).add(hb.shard)
        return sorted(of for of, shards in cov.items()
                      if len(shards) == of)

    def _commit_locked(self, new_of: int) -> None:
        old = self._merged_of
        if old and old != new_of:
            # atomic drain: the instant the new topology is fully
            # covered the old one retires — its entries purge NOW, its
            # later heartbeats drop with the stale counter, and no
            # request ever merges shards of two topologies
            self._retired.add(old)
            self.topology_cutovers += 1
            self._replicas = {rid: (hb, seen)
                              for rid, (hb, seen) in self._replicas.items()
                              if hb.of != old}
            _log.warning("Topology cutover: %d-way -> %d-way "
                         "(old fleet retired)", old, new_of)
        self._merged_of = new_of
        self._merged_last_live = self._clock()
        self._retired.discard(new_of)
        if self._target_of == new_of:
            self._target_of = None

    def _topology_locked(self) -> int:
        """The routed shard count, advancing the topology state machine
        (see module docstring): commit at bootstrap or cut over to a
        fully-ready warming topology; otherwise hold the merged one."""
        full = self._full_coverage_locked()
        if self._merged_of == 0:
            if full:
                self._commit_locked(max(full))
                return self._merged_of
            live = self._live_locked()
            if live:
                # provisional (uncommitted): route the largest topology
                # announced so bring-up serves partial answers instead
                # of nothing
                return max(hb.of for hb in live)
            return max(1, self._of)
        candidates = [of for of in full
                      if of != self._merged_of and of not in self._retired]
        if candidates:
            now = self._clock()
            if self._target_of in candidates:
                self._commit_locked(self._target_of)
            elif (not self._merged_live_locked(now)
                    and self._merged_grace_expired_locked(now)):
                # merged fleet silent past the grace window (dead, not
                # blinking): re-bootstrap onto the fully-covered
                # survivor
                self._commit_locked(max(candidates))
        return self._merged_of

    def _ranked_locked(self, live: list[Heartbeat], shard: int,
                       of: int) -> list[Heartbeat]:
        """One shard's ready candidates, ranked: newest generation
        first, rotated by the shared round-robin counter so repeated
        calls spread load; older-generation replicas stay at the tail
        — a hedge may still fall back to them (stale beats dead), but
        a replica mid-replay of a newer model is ranked behind its
        peers.  THE single ranking definition: candidates() and
        routing_plan() must never disagree on ordering."""
        sl = [hb for hb in live
              if hb.shard == shard and hb.ready and hb.of == of]
        if not sl:
            return []
        top_gen = max(hb.generation for hb in sl)
        newest = [hb for hb in sl if hb.generation == top_gen]
        older = [hb for hb in sl if hb.generation != top_gen]
        self._rr += 1
        r = self._rr % len(newest)
        older.sort(key=lambda hb: -hb.generation)
        return newest[r:] + newest[:r] + older

    def candidates(self, shard: int) -> list[Heartbeat]:
        """Ready live replicas for a shard IN THE CURRENT TOPOLOGY —
        the shard's replica group (see _ranked_locked for the
        ordering)."""
        with self._lock:
            of = self._topology_locked()
            return self._ranked_locked(self._live_locked(), shard, of)

    def routing_plan(self) -> tuple[int, list[list[Heartbeat]]]:
        """One CONSISTENT snapshot of (routed topology, per-shard ready
        candidate lists) under a SINGLE lock acquisition — the scatter
        fan-out's view of the cluster.  The per-shard ``candidates()``
        calls each re-derive the topology, so a cutover landing between
        two of them could hand one request shard 0 of the OLD ring and
        shard 1 of the NEW one: overlapping catalogs merged as if
        disjoint, a silently wrong 200 with no partial marker.  The
        atomic-cutover contract ("a request routes either entirely old
        or entirely new", module docstring) therefore requires the
        whole plan to come from one locked read.  Ordering per shard
        is _ranked_locked — the same definition ``candidates()``
        uses."""
        with self._lock:
            of = self._topology_locked()
            live = self._live_locked()
            return of, [self._ranked_locked(live, shard, of)
                        for shard in range(of)]

    def any_candidates(self) -> list[Heartbeat]:
        """Ready live replicas of ANY shard in the current topology
        (for endpoints served from the replicated user store), newest
        generation first — rotation for load spreading happens WITHIN
        the newest generation only, the same contract as
        ``candidates()``, so a replica still replaying an older model
        is never ranked ahead of an up-to-date one."""
        with self._lock:
            of = self._topology_locked()
            live = [hb for hb in self._live_locked()
                    if hb.ready and hb.of == of]
            if not live:
                return []
            top_gen = max(hb.generation for hb in live)
            newest = [hb for hb in live if hb.generation == top_gen]
            older = [hb for hb in live if hb.generation != top_gen]
            older.sort(key=lambda hb: -hb.generation)
            self._rr += 1
            r = self._rr % len(newest)
            return newest[r:] + newest[:r] + older

    def generation_topology(self) -> tuple[int, tuple[int, ...], bool]:
        """The result cache's epoch: (routed topology, per-shard newest
        ready generation with -1 for an uncovered shard, and a MIXED
        flag).  Keying cached answers by the first two means a
        generation rollout or topology cutover changes the key
        shard-by-shard as heartbeats flip.  ``mixed`` is True while any
        shard's replica group spans generations: during that window a
        hedge may fall back to an older-generation sibling and win, so
        a complete answer is NOT provably of the newest generation —
        the cache refuses to serve or store until the group converges
        (cluster/result_cache.py; the MODEL-publish flush reclaims the
        previous epoch's bytes)."""
        with self._lock:
            of = self._topology_locked()
            gens = [-1] * of
            mixed = False
            for hb in self._live_locked():
                if hb.ready and hb.of == of and 0 <= hb.shard < of:
                    prev = gens[hb.shard]
                    if prev != -1 and prev != hb.generation:
                        mixed = True
                    gens[hb.shard] = max(prev, hb.generation)
            return of, tuple(gens), mixed

    def covered_shards(self) -> list[int]:
        with self._lock:
            of = self._topology_locked()
            return sorted({hb.shard for hb in self._live_locked()
                           if hb.ready and hb.of == of})

    def group_sizes(self) -> dict[int, int]:
        """shard -> live ready replica-group size in the merged
        topology — in-process introspection for tests and embedders
        (the autoscaler, a separate process, derives the same map from
        the router's /metrics membership snapshot)."""
        with self._lock:
            of = self._topology_locked()
            out: dict[int, int] = {s: 0 for s in range(of)}
            for hb in self._live_locked():
                if hb.ready and hb.of == of:
                    out[hb.shard] = out.get(hb.shard, 0) + 1
            return out

    def _status_locked(self) -> dict:
        """Reshard/topology status (the /admin/topology view): per live
        topology, its coverage toward cutover and the slowest member's
        warm fraction."""
        merged = self._topology_locked()
        by_of: dict[int, dict] = {}
        for hb in self._live_locked():
            st = by_of.setdefault(hb.of, {
                "replicas": 0, "ready_shards": set(), "min_fraction": 1.0})
            st["replicas"] += 1
            if hb.ready:
                st["ready_shards"].add(hb.shard)
            st["min_fraction"] = min(st["min_fraction"], hb.fraction)
        return {
            "merged_of": merged,
            "reshard_target": self._target_of,
            "retired": sorted(self._retired),
            "topology_cutovers": self.topology_cutovers,
            "stale_topology_heartbeats": self.stale_topology_heartbeats,
            "topologies": {
                str(of): {
                    "replicas": st["replicas"],
                    "ready_shards": len(st["ready_shards"]),
                    "of": of,
                    "full_coverage": len(st["ready_shards"]) == of,
                    "min_fraction": round(st["min_fraction"], 4),
                    "state": ("merged" if of == merged else
                              "warming" if of == self._target_of
                              else "observed"),
                }
                for of, st in sorted(by_of.items())},
        }

    def topology_status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def snapshot(self) -> dict:
        """Operator view for the router's /metrics."""
        with self._lock:
            now = self._clock()
            return {
                # the CURRENT routed topology, not the largest ever
                # seen: after a reshard down, routing follows the live
                # `of` and the operator view must agree with it
                "shards": self._topology_locked(),
                "reshard_target": self._target_of,
                "heartbeats_seen": self.heartbeats_seen,
                "stale_topology_heartbeats":
                    self.stale_topology_heartbeats,
                "topology_cutovers": self.topology_cutovers,
                "replicas": {
                    rid: {"shard": hb.shard, "of": hb.of, "url": hb.url,
                          "generation": hb.generation, "ready": hb.ready,
                          "fraction": round(hb.fraction, 4),
                          "age_sec": round(now - seen, 3),
                          "live": now - seen <= self.ttl_sec}
                    for rid, (hb, seen) in sorted(self._replicas.items())},
            }


class HeartbeatPublisher:
    """Replica-side heartbeat loop (a daemon thread owned by the
    serving layer).  Publish failures are logged and retried next
    interval — a replica that cannot reach the broker ages out of the
    router's registry, which IS the designed degrade.  Chaos seams:
    ``replica-heartbeat-drop`` suppresses sends (a partitioned-but-
    alive replica); ``replica-group-flap`` (mode=delay just past the
    TTL) makes beats straggle so the replica oscillates in and out of
    routing — the no-oscillation-churn test handle."""

    def __init__(self, producer, shard: int, of: int, url: str,
                 manager, min_fraction: float,
                 interval_sec: float = 0.5,
                 replica_id: str | None = None,
                 region: str | None = None,
                 tport: int | None = None):
        self._producer = producer
        self.shard = shard
        self.of = of
        self.url = url
        self._manager = manager
        self._min_fraction = min_fraction
        self.interval_sec = interval_sec
        self.replica_id = replica_id or uuid.uuid4().hex[:12]
        self.region = region
        self.tport = tport
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.published = 0

    def current_heartbeat(self) -> Heartbeat:
        model = self._manager.get_model()
        fraction = model.get_fraction_loaded() if model is not None else 0.0
        return Heartbeat(
            replica=self.replica_id, shard=self.shard, of=self.of,
            url=self.url,
            generation=int(getattr(self._manager, "generation", 0)),
            ready=model is not None and fraction >= self._min_fraction,
            fraction=fraction, ts=clockmod.now(), region=self.region,
            tport=self.tport)

    def publish_once(self) -> bool:
        if faults.fire("replica-heartbeat-drop") == "drop":
            return False  # chaos: alive but silent -> ages out of routing
        # flap chaos: mode=delay with delay-ms slightly past the TTL
        # stretches the inter-beat gap so the replica keeps aging out
        # and returning; mode=drop skips single beats
        if faults.fire("replica-group-flap") == "drop":
            return False
        try:
            self._producer.send(KEY_HEARTBEAT,
                                self.current_heartbeat().to_json())
            self.published += 1
            return True
        except Exception:  # noqa: BLE001 — next interval retries
            _log.warning("heartbeat publish failed; replica will age "
                         "out of routing until the broker returns",
                         exc_info=True)
            return False

    def _run(self) -> None:
        while not self._stop.is_set():
            self.publish_once()
            clockmod.wait(self._stop, self.interval_sec)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ClusterHeartbeat")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
