"""Replica membership: heartbeats on the update topic + the router's
live registry.

Replicas publish small JSON heartbeats under the ``HB`` key on the
same update topic that carries MODEL/MODEL-REF/UP — no extra
infrastructure, and the router discovers replicas by tailing the topic
it already understands.  Every update-topic consumer that is not the
router must skip ``HB`` records (:func:`without_heartbeats`); they are
control-plane traffic, not model state.

A heartbeat carries the replica's shard assignment, its public URL,
the model *generation* it is currently serving (count of accepted
MODEL/MODEL-REF documents since replay offset 0 — identical across
replicas because the update topic is totally ordered), and a ``ready``
flag (fraction loaded past the serving gate).  The registry routes
only to ready replicas and, within a shard, prefers the newest
generation — a replica still replaying an older model is never routed.

Liveness is judged by *receive* time (router monotonic clock), not the
sender's timestamp, so clock skew between hosts cannot fake liveness.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Iterable, Iterator

from ..kafka.api import KeyMessage
from ..resilience import faults

_log = logging.getLogger(__name__)

__all__ = ["KEY_HEARTBEAT", "Heartbeat", "MembershipRegistry",
           "HeartbeatPublisher", "without_heartbeats"]

# update-topic key for replica heartbeats (rides next to MODEL/UP;
# consumers that build model state skip it)
KEY_HEARTBEAT = "HB"


def without_heartbeats(updates: Iterable[KeyMessage]) -> Iterator[KeyMessage]:
    """Drop cluster heartbeats from an update-topic stream — the filter
    every model-state consumer (serving/speed) tails through."""
    return (km for km in updates if km.key != KEY_HEARTBEAT)


@dataclass
class Heartbeat:
    replica: str          # stable per-process id
    shard: int            # catalog shard this replica serves
    of: int               # total shard count the replica was started with
    url: str              # public base URL, e.g. http://10.0.0.3:8080
    generation: int       # accepted MODEL documents since replay offset 0
    ready: bool           # fraction loaded past the serving gate
    fraction: float = 0.0
    ts: float = 0.0       # sender wall clock (diagnostic only)

    def to_json(self) -> str:
        return json.dumps(self.__dict__, separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "Heartbeat | None":
        try:
            d = json.loads(s)
            return cls(replica=str(d["replica"]), shard=int(d["shard"]),
                       of=int(d["of"]), url=str(d["url"]),
                       generation=int(d["generation"]),
                       ready=bool(d["ready"]),
                       fraction=float(d.get("fraction", 0.0)),
                       ts=float(d.get("ts", 0.0)))
        except (ValueError, TypeError, KeyError):
            return None  # malformed control message: ignore, don't die


class MembershipRegistry:
    """Router-side view of the cluster, built from heartbeats.

    ``candidates(shard)`` returns the ready replicas of a shard, newest
    generation first (ties rotated round-robin for load spreading).
    ``shard_count`` is learned from heartbeats (the max ``of``
    announced), so the router needs no shard-count config of its own
    and reports partial answers as ``m/N`` against the true topology.
    """

    def __init__(self, ttl_sec: float, clock=time.monotonic):
        self.ttl_sec = ttl_sec
        self._clock = clock
        self._lock = threading.Lock()
        # replica id -> (Heartbeat, last_seen_monotonic)
        self._replicas: dict[str, tuple[Heartbeat, float]] = {}
        self._of = 0
        self._rr = 0
        self.heartbeats_seen = 0

    def note(self, hb: Heartbeat) -> None:
        with self._lock:
            self.heartbeats_seen += 1
            self._replicas[hb.replica] = (hb, self._clock())
            if hb.of > self._of:
                self._of = hb.of

    def note_message(self, message: str) -> None:
        hb = Heartbeat.from_json(message)
        if hb is not None:
            self.note(hb)
        else:
            _log.warning("Malformed heartbeat ignored")

    @property
    def shard_count(self) -> int:
        with self._lock:
            return self._topology_locked()

    def _live_locked(self) -> list[Heartbeat]:
        now = self._clock()
        return [hb for hb, seen in self._replicas.values()
                if now - seen <= self.ttl_sec]

    def _topology_locked(self) -> int:
        """The cluster's CURRENT shard count: the largest ``of`` among
        live replicas (falling back to the largest ever seen while
        nothing is live).  Exactness requires merging replicas of ONE
        topology only — a 1-way replica's catalog overlaps a 2-way
        shard's, so mixing ``of`` values in a merge would duplicate
        items; candidates() filters accordingly, which also makes a
        reshard (start N'-way replicas, stop the old ones) cut over
        atomically once the new topology's heartbeats dominate."""
        live = self._live_locked()
        if live:
            return max(hb.of for hb in live)
        return max(1, self._of)

    def candidates(self, shard: int) -> list[Heartbeat]:
        """Ready live replicas for a shard IN THE CURRENT TOPOLOGY:
        newest generation first; within a generation, rotated so
        repeated calls spread load."""
        with self._lock:
            of = self._topology_locked()
            live = [hb for hb in self._live_locked()
                    if hb.shard == shard and hb.ready and hb.of == of]
            if not live:
                return []
            top_gen = max(hb.generation for hb in live)
            newest = [hb for hb in live if hb.generation == top_gen]
            older = [hb for hb in live if hb.generation != top_gen]
            self._rr += 1
            r = self._rr % len(newest)
            # older-generation replicas stay at the tail: a hedge may
            # still fall back to them (stale beats dead), but a replica
            # mid-replay of a newer model is ranked behind its peers
            older.sort(key=lambda hb: -hb.generation)
            return newest[r:] + newest[:r] + older

    def any_candidates(self) -> list[Heartbeat]:
        """Ready live replicas of ANY shard in the current topology
        (for endpoints served from the replicated user store), newest
        generation first — rotation for load spreading happens WITHIN
        the newest generation only, the same contract as
        ``candidates()``, so a replica still replaying an older model
        is never ranked ahead of an up-to-date one."""
        with self._lock:
            of = self._topology_locked()
            live = [hb for hb in self._live_locked()
                    if hb.ready and hb.of == of]
            if not live:
                return []
            top_gen = max(hb.generation for hb in live)
            newest = [hb for hb in live if hb.generation == top_gen]
            older = [hb for hb in live if hb.generation != top_gen]
            older.sort(key=lambda hb: -hb.generation)
            self._rr += 1
            r = self._rr % len(newest)
            return newest[r:] + newest[:r] + older

    def covered_shards(self) -> list[int]:
        with self._lock:
            of = self._topology_locked()
            return sorted({hb.shard for hb in self._live_locked()
                           if hb.ready and hb.of == of})

    def snapshot(self) -> dict:
        """Operator view for the router's /metrics."""
        with self._lock:
            now = self._clock()
            return {
                # the CURRENT routed topology, not the largest ever
                # seen: after a reshard down, routing follows the live
                # `of` and the operator view must agree with it
                "shards": self._topology_locked(),
                "heartbeats_seen": self.heartbeats_seen,
                "replicas": {
                    rid: {"shard": hb.shard, "of": hb.of, "url": hb.url,
                          "generation": hb.generation, "ready": hb.ready,
                          "fraction": round(hb.fraction, 4),
                          "age_sec": round(now - seen, 3),
                          "live": now - seen <= self.ttl_sec}
                    for rid, (hb, seen) in sorted(self._replicas.items())},
            }


class HeartbeatPublisher:
    """Replica-side heartbeat loop (a daemon thread owned by the
    serving layer).  Publish failures are logged and retried next
    interval — a replica that cannot reach the broker ages out of the
    router's registry, which IS the designed degrade.  The
    ``replica-heartbeat-drop`` fault point suppresses sends for chaos
    tests (a partitioned-but-alive replica)."""

    def __init__(self, producer, shard: int, of: int, url: str,
                 manager, min_fraction: float,
                 interval_sec: float = 0.5,
                 replica_id: str | None = None):
        self._producer = producer
        self.shard = shard
        self.of = of
        self.url = url
        self._manager = manager
        self._min_fraction = min_fraction
        self.interval_sec = interval_sec
        self.replica_id = replica_id or uuid.uuid4().hex[:12]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.published = 0

    def current_heartbeat(self) -> Heartbeat:
        model = self._manager.get_model()
        fraction = model.get_fraction_loaded() if model is not None else 0.0
        return Heartbeat(
            replica=self.replica_id, shard=self.shard, of=self.of,
            url=self.url,
            generation=int(getattr(self._manager, "generation", 0)),
            ready=model is not None and fraction >= self._min_fraction,
            fraction=fraction, ts=time.time())

    def publish_once(self) -> bool:
        if faults.fire("replica-heartbeat-drop") == "drop":
            return False  # chaos: alive but silent -> ages out of routing
        try:
            self._producer.send(KEY_HEARTBEAT,
                                self.current_heartbeat().to_json())
            self.published += 1
            return True
        except Exception:  # noqa: BLE001 — next interval retries
            _log.warning("heartbeat publish failed; replica will age "
                         "out of routing until the broker returns",
                         exc_info=True)
            return False

    def _run(self) -> None:
        while not self._stop.is_set():
            self.publish_once()
            self._stop.wait(self.interval_sec)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ClusterHeartbeat")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
