"""Catalog sharding: stable item id -> shard index.

The hash is the Kafka DefaultPartitioner contract
(kafka/partitioner.py), so shard assignment is a pure, spec-pinned
function of the id and the shard count — every replica, the router,
and any future rebalancer agree with no coordination.  The full USER
store is replicated to every shard (user vectors and known-items are
tiny next to a 20M-item catalog and are needed for local exclusion),
so only Y/item state is sharded.
"""

from __future__ import annotations

from ..kafka.partitioner import partition_for_key

__all__ = ["shard_of", "parse_shard_spec", "is_local_item"]


def shard_of(item_id: str, shard_count: int) -> int:
    """The shard that owns ``item_id`` in an ``shard_count``-way
    catalog split."""
    if shard_count <= 1:
        return 0
    return partition_for_key(item_id, shard_count)


def parse_shard_spec(spec: str) -> tuple[int, int]:
    """``"i/N"`` -> (shard_index, shard_count), validated."""
    try:
        idx_s, count_s = spec.split("/", 1)
        idx, count = int(idx_s), int(count_s)
    except ValueError as e:
        raise ValueError(f"shard spec must be 'i/N', got {spec!r}") from e
    if count < 1 or not 0 <= idx < count:
        raise ValueError(f"shard index out of range in {spec!r}")
    return idx, count


def is_local_item(item_id: str, shard_index: int, shard_count: int) -> bool:
    return shard_count <= 1 or shard_of(item_id, shard_count) == shard_index
