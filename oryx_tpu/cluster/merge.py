"""Exact top-N merge and the cluster's canonical result order.

The cluster must return the SAME answer as a single node holding the
full catalog, for any shard count — ties included.  Device ``top_k``
breaks score ties by store row index, an artifact of each process's
own free-row recycling that no other process can reproduce.  The
cluster therefore defines ONE canonical total order and applies it on
every path:

    (score descending, ordinal ascending, id ascending)

where ``ordinal`` is the item's first-appearance index in the totally
ordered update topic (assigned by every consumer identically —
ALSServingModelManager.item_ordinals).  A 1-shard replica and an
N-shard merge sort identical per-item (score, ordinal) pairs, so the
merged result is byte-identical to the single-node exact scan
(tests/test_cluster_merge.py drives random catalogs / shardings /
ties / retired rows through exactly this claim).

Exactness needs each shard's *local* top-k to be exact under the
canonical order too: :func:`exact_local_top_n` detects a tie group
straddling the local k-boundary (where the device's row-order pick is
not canonical) and widens the fetch window until the boundary tie
group is fully in view — the same fetched device scores, never a
recompute, so scores stay bit-identical to the plain serving path.
"""

from __future__ import annotations

from typing import Sequence

__all__ = ["canon_sort", "merge_top_n", "exact_local_top_n"]

# rows travel between shard and router as [id, score, ordinal]
Row = tuple[str, float, int]


def _key(row: Row, lowest: bool):
    # NaN-free by construction (serving filters non-finite scores);
    # -score gives descending score, ordinal ascending breaks ties.
    # The id is a final key so the order stays TOTAL even for items
    # that never got a replay ordinal (models built outside the
    # update-topic replay, e.g. bench factories).
    return (row[1] if lowest else -row[1], row[2], row[0])


def canon_sort(rows: Sequence[Row], lowest: bool = False) -> list[Row]:
    return sorted(rows, key=lambda r: _key(r, lowest))


def merge_top_n(shard_rows: Sequence[Sequence[Row]], how_many: int,
                offset: int = 0, lowest: bool = False) -> list[Row]:
    """Merge per-shard exact local top-k lists into the exact global
    top-``how_many`` after ``offset`` under the canonical order.
    Exact because catalog shards are disjoint and each shard list is
    its exact local prefix of length >= offset + how_many (or its
    whole catalog's survivors)."""
    merged: list[Row] = []
    for rows in shard_rows:
        merged.extend((r[0], r[1], r[2]) for r in rows)
    return canon_sort(merged, lowest)[offset:offset + how_many]


def exact_local_top_n(model, ordinal_of, how_many: int, *,
                      user_vector=None, cosine_to=None,
                      exclude=(), rescorer=None, allowed=None,
                      lowest: bool = False,
                      use_lsh: bool = True,
                      batcher=None, deadline=None) -> list[Row]:
    """This shard's exact top-``how_many`` under the canonical order,
    as (id, score, ordinal) rows.

    Fast path (no rescorer/allowed): fetch ``how_many + 1`` through the
    normal device scan; when the boundary score is strictly separated,
    the top-k SET is unique and only needs the canonical re-sort.  A
    tie group straddling the boundary widens the window (doubling)
    until every member of the boundary tie group is in view, then
    fills canonically.  Rescorer / allowed-predicate queries rank by
    POST-rescore score, for which no raw-score window bound exists —
    those take the full exact scan (``how_many`` = whole catalog),
    which is also exactly what makes a 1-shard replica the reference
    semantics for the property tests.
    """
    exclude = set(exclude)
    kw = dict(user_vector=user_vector, cosine_to=cosine_to,
              exclude=exclude, lowest=lowest, use_lsh=use_lsh)

    def _rows(pairs) -> list[Row]:
        return [(i, s, ordinal_of(i)) for i, s in pairs]

    n_live = model.item_count()
    if n_live == 0 or how_many <= 0:
        return []
    if rescorer is not None or allowed is not None:
        pairs = model.top_n(n_live, rescorer=rescorer, allowed=allowed,
                            **kw)
        return canon_sort(_rows(pairs), lowest)[:how_many]

    def fetch(m: int):
        # plain dot queries coalesce with concurrent shard requests
        # through the app-scope batcher (same pairs as model.top_n —
        # serving throughput must not regress because a gateway fronts
        # the replica); cosine/lowest take the direct path
        if batcher is not None and user_vector is not None \
                and not lowest and use_lsh:
            return batcher.top_n(model, m, user_vector, exclude,
                                 deadline=deadline)
        if deadline is not None:
            deadline.check("shard top_n")
        return model.top_n(m, **kw)

    # capacity bound: once the request window covers every store row,
    # the fetch is complete no matter how deep the tie group runs
    capacity = len(model.Y.row_ids())
    m = how_many + 1
    while True:
        pairs = fetch(m)
        if len(pairs) <= how_many:
            # fewer live candidates than asked: everything is in view
            return canon_sort(_rows(pairs), lowest)
        boundary = pairs[how_many - 1][1]
        # the fetch is complete when it returned FEWER pairs than asked
        # (top_n full-scans whenever filtering eats its padded window,
        # so a short answer means every live non-excluded candidate is
        # in view) or the request itself covers every store row.  The
        # exclude size must NOT count toward coverage: on a sharded
        # replica the exclude set is the user's GLOBAL known items,
        # most of which occupy no local row — counting them stopped
        # the widening loop with live tied candidates still unfetched.
        complete = len(pairs) < m or m >= capacity
        # pairs arrive sorted by score (desc, or asc under lowest); the
        # boundary tie group is fully in view once the tail score has
        # strictly passed it
        tail_past = (pairs[-1][1] < boundary if not lowest
                     else pairs[-1][1] > boundary)
        if tail_past or complete:
            head = [r for r in _rows(pairs)
                    if (r[1] > boundary if not lowest else r[1] < boundary)]
            tied = [r for r in _rows(pairs) if r[1] == boundary]
            out = canon_sort(head, lowest) + canon_sort(tied, lowest)
            return out[:how_many]
        m = min(max(m * 2, 16), capacity)
