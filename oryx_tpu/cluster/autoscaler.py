"""Gauge-driven supervisor: spawn/retire replica-group members from
the cluster's own measured signals.

``python -m oryx_tpu autoscale`` closes the loop the observability
layer opened: the router already publishes exactly-mergeable latency
buckets, the scatter path already measures the cluster's scoring queue
wait, and every replica already reports its update-topic lag — this
process polls those gauges against configured thresholds
(``oryx.cluster.autoscale.*``) and changes the FLEET, not the config:
a breaching p99/queue-wait spawns one more member into the thinnest
shard's replica group; a sustained calm retires one.  Members are
ordinary ``serving --shard i/N`` processes run under the PR-1
:class:`~oryx_tpu.resilience.policy.Supervisor` (restart-with-backoff
around the process lifecycle), and membership propagates through the
normal heartbeat protocol — the router needs no notification, the
autoscaler no registry of its own.

Decision discipline (the anti-flap rules every production autoscaler
converges on):

- signals must breach for ``scale-up-after`` CONSECUTIVE polls (one
  slow scrape never scales), and stay calm for the much longer
  ``scale-down-after`` before a retire;
- after any action a ``cooldown-ms`` window lets the fleet settle —
  a spawned member needs a full update-topic replay before it takes
  load, and acting again on the pre-warm signal would overshoot;
- p99 is computed over the INTERVAL between polls (bucket-count
  deltas, ``obs/prom.py bucket_quantile``), never over process
  lifetime — a counter's history must not vote on current load;
- scale-down retires only members THIS supervisor spawned, never the
  statically deployed fleet, and never below
  ``min-replicas-per-shard`` live members.

The decision core (:meth:`Autoscaler.step`) is pure given a
:class:`Signals` snapshot, so the policy is unit-testable without a
cluster; the HTTP polling and process spawning live behind small
seams (``fetch_json``, :class:`ReplicaLauncher`).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import urllib.request
from dataclasses import dataclass, field

from ..common import clock as clockmod
from ..obs.prom import LATENCY_BUCKETS_MS, bucket_quantile
from ..obs.slo import is_data_plane as _data_plane
from ..resilience.policy import Supervisor

_log = logging.getLogger(__name__)

__all__ = ["Signals", "AutoscalePolicy", "Autoscaler",
           "ReplicaLauncher", "ProcessReplicaLauncher", "run_autoscaler"]


@dataclass
class Signals:
    """One poll's view of the cluster (None = signal unavailable)."""
    ok: bool = False
    merged_of: int = 0
    group_sizes: dict = field(default_factory=dict)  # shard -> members
    p99_ms: float | None = None          # interval p99, data plane
    queue_wait_ms: float | None = None   # scatter's admission signal
    update_lag_records: float | None = None  # worst replica
    slo_burn_rate: float | None = None   # router's SLO engine (obs/slo)


@dataclass
class AutoscalePolicy:
    p99_high_ms: float = 500.0
    p99_low_ms: float = 50.0
    queue_wait_high_ms: float = 200.0
    update_lag_high_records: float = 0.0
    slo_burn_high: float = 0.0
    scale_up_after: int = 2
    scale_down_after: int = 12
    cooldown_sec: float = 15.0
    min_replicas_per_shard: int = 1
    max_replicas_per_shard: int = 4

    @classmethod
    def from_config(cls, config) -> "AutoscalePolicy":
        c = "oryx.cluster.autoscale"
        return cls(
            p99_high_ms=config.get_int(f"{c}.p99-high-ms"),
            p99_low_ms=config.get_int(f"{c}.p99-low-ms"),
            queue_wait_high_ms=config.get_int(f"{c}.queue-wait-high-ms"),
            update_lag_high_records=config.get_int(
                f"{c}.update-lag-high-records"),
            slo_burn_high=config.get_double(f"{c}.slo-burn-high"),
            scale_up_after=max(1, config.get_int(f"{c}.scale-up-after")),
            scale_down_after=max(
                1, config.get_int(f"{c}.scale-down-after")),
            cooldown_sec=config.get_int(f"{c}.cooldown-ms") / 1000.0,
            min_replicas_per_shard=max(1, config.get_int(
                f"{c}.min-replicas-per-shard")),
            max_replicas_per_shard=max(1, config.get_int(
                f"{c}.max-replicas-per-shard")))

    def pressure(self, s: Signals) -> list[str]:
        """Breaching scale-up signals, named for the log/status."""
        out = []
        if self.p99_high_ms > 0 and s.p99_ms is not None \
                and s.p99_ms > self.p99_high_ms:
            out.append(f"p99 {s.p99_ms:.0f}ms > {self.p99_high_ms:.0f}")
        if self.queue_wait_high_ms > 0 and s.queue_wait_ms is not None \
                and s.queue_wait_ms > self.queue_wait_high_ms:
            out.append(f"queue_wait {s.queue_wait_ms:.0f}ms > "
                       f"{self.queue_wait_high_ms:.0f}")
        if self.update_lag_high_records > 0 \
                and s.update_lag_records is not None \
                and s.update_lag_records > self.update_lag_high_records:
            out.append(f"update_lag {s.update_lag_records:.0f} > "
                       f"{self.update_lag_high_records:.0f}")
        if self.slo_burn_high > 0 and s.slo_burn_rate is not None \
                and s.slo_burn_rate > self.slo_burn_high:
            # error-budget burn (obs/slo.py): capacity is added while
            # the budget still exists, not after the SLO is blown —
            # scaling on burn rate instead of a raw latency threshold
            # is what ties the fleet size to the objective
            out.append(f"slo_burn {s.slo_burn_rate:.1f} > "
                       f"{self.slo_burn_high:.1f}")
        return out

    def calm(self, s: Signals) -> bool:
        """True when the cluster is demonstrably under-loaded (scale-
        down evidence).  p99 None (no data-plane traffic at all this
        interval) counts as calm."""
        if self.p99_low_ms <= 0:
            return False  # scale-down disabled
        if self.pressure(s):
            return False
        return s.p99_ms is None or s.p99_ms <= self.p99_low_ms


class ReplicaLauncher:
    """What the decision loop needs from the process layer.  The
    production implementation is :class:`ProcessReplicaLauncher`;
    tests substitute a fake."""

    def spawn(self, shard: int, of: int) -> str:
        raise NotImplementedError

    def retire(self, shard: int, of: int) -> str | None:
        """Stop one member of (shard, of) that THIS launcher spawned;
        None when it owns none there."""
        raise NotImplementedError

    def owned(self, of: int) -> dict[int, int]:
        """shard -> members this launcher currently runs for topology
        ``of``."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class _MemberProcess:
    """start()/await_()/close() facade over one spawned ``serving
    --shard i/N`` OS process, so the resilience Supervisor's layer
    contract applies to processes unchanged: await_ returning while
    close was never requested IS the crash signal, and the Supervisor
    rebuilds (re-spawns) with backoff."""

    def __init__(self, argv: list[str], log_path: str, env: dict):
        self._argv = argv
        self._log_path = log_path
        self._env = env
        self._proc = None
        self._closing = False

    def start(self) -> None:
        import subprocess
        with open(self._log_path, "ab") as log:
            self._proc = subprocess.Popen(self._argv, env=self._env,
                                          stdout=log, stderr=log)

    def await_(self) -> None:
        if self._proc is not None:
            self._proc.wait()
        if not self._closing and self._proc is not None \
                and self._proc.returncode not in (0, None):
            raise RuntimeError(
                f"member exited with {self._proc.returncode}")

    def close(self) -> None:
        self._closing = True
        if self._proc is None:
            return
        self._proc.terminate()
        try:
            self._proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — escalate to kill
            self._proc.kill()


class _Member:
    __slots__ = ("member_id", "shard", "of", "supervisor", "thread")

    def __init__(self, member_id, shard, of, supervisor, thread):
        self.member_id = member_id
        self.shard = shard
        self.of = of
        self.supervisor = supervisor
        self.thread = thread


class ProcessReplicaLauncher(ReplicaLauncher):
    """Spawn supervised ``python -m oryx_tpu serving --shard i/N``
    member processes.  Each member gets a derived conf — the base conf
    text with member keys appended (HOCON last-wins): cluster mode on,
    its shard spec, a stable replica id, and an ephemeral API port so
    N members coexist on one host (heartbeats advertise the real bound
    port)."""

    def __init__(self, config, base_conf_text: str, work_dir: str,
                 python: str = sys.executable):
        self._config = config
        self._base = base_conf_text
        self._work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        self._python = python
        self._members: list[_Member] = []
        self._seq = 0
        self._lock = threading.Lock()

    def _member_conf(self, member_id: str, shard: int, of: int) -> str:
        path = os.path.join(self._work_dir, f"{member_id}.conf")
        overrides = "\n".join([
            "",
            "# appended by the autoscaler (HOCON last-wins)",
            "oryx.cluster.enabled = true",
            f'oryx.cluster.shard = "{shard}/{of}"',
            f'oryx.cluster.replica-id = "{member_id}"',
            "oryx.serving.api.port = 0",
            "", ])
        with open(path, "w", encoding="utf-8") as f:
            f.write(self._base + overrides)
        return path

    def spawn(self, shard: int, of: int) -> str:
        with self._lock:
            self._seq += 1
            member_id = f"asg-{shard}of{of}-{self._seq}"
        conf = self._member_conf(member_id, shard, of)
        argv = [self._python, "-m", "oryx_tpu", "serving",
                "--shard", f"{shard}/{of}", "--conf", conf]
        log_path = os.path.join(self._work_dir, f"{member_id}.log")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")

        supervisor = Supervisor.from_config(
            lambda: _MemberProcess(argv, log_path, env),
            f"autoscale-member[{member_id}]", self._config)
        thread = threading.Thread(target=self._run_supervised,
                                  args=(supervisor, member_id),
                                  daemon=True,
                                  name=f"Autoscale-{member_id}")
        member = _Member(member_id, shard, of, supervisor, thread)
        with self._lock:
            self._members.append(member)
        thread.start()
        _log.info("spawned member %s (shard %d/%d)", member_id, shard,
                  of)
        return member_id

    @staticmethod
    def _run_supervised(supervisor: Supervisor, member_id: str) -> None:
        try:
            supervisor.run()
        except Exception:  # noqa: BLE001 — restart budget exhausted
            _log.exception("member %s gave up", member_id)

    def _stop_member(self, member: _Member) -> None:
        member.supervisor.stop()
        if member.supervisor.layer is not None:
            try:
                member.supervisor.layer.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                _log.exception("closing member %s failed",
                               member.member_id)
        member.thread.join(15.0)

    def retire(self, shard: int, of: int) -> str | None:
        with self._lock:
            idx = next((i for i in range(len(self._members) - 1, -1, -1)
                        if self._members[i].shard == shard
                        and self._members[i].of == of), None)
            if idx is None:
                return None
            member = self._members.pop(idx)
        self._stop_member(member)
        _log.info("retired member %s (shard %d/%d)", member.member_id,
                  shard, of)
        return member.member_id

    def owned(self, of: int) -> dict[int, int]:
        with self._lock:
            out: dict[int, int] = {}
            for m in self._members:
                if m.of == of:
                    out[m.shard] = out.get(m.shard, 0) + 1
            return out

    def close(self) -> None:
        with self._lock:
            members, self._members = self._members, []
        for m in members:
            self._stop_member(m)


def fetch_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read() or b"null")


class Autoscaler:
    """Poll → decide → act.  ``step(signals, now)`` is the pure
    decision core; ``poll_signals`` is the HTTP half; ``run`` the
    loop."""

    def __init__(self, policy: AutoscalePolicy,
                 launcher: ReplicaLauncher, router_url: str,
                 poll_interval_sec: float = 5.0, metrics=None,
                 fetch=fetch_json, clock=clockmod.monotonic):
        self.policy = policy
        self.launcher = launcher
        self.router_url = router_url.rstrip("/")
        self.poll_interval_sec = poll_interval_sec
        self.metrics = metrics
        self._fetch = fetch
        self._clock = clock
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown_until = 0.0
        self.actions: list[dict] = []
        # previous cumulative data-plane bucket counts (interval p99)
        self._prev_buckets: list[int] | None = None
        # counter-reset discards: a restarted process's cumulative
        # buckets went backwards, so that interval's delta is garbage
        self.counter_resets = 0

    # -- signal collection ---------------------------------------------------

    def _interval_p99(self, prom_snap: dict) -> float | None:
        """p99 over the polls' interval: data-plane bucket-count deltas
        against the previous poll (cumulative counters must not let
        history vote on current load).

        Monotonicity guard: cumulative counters only ever grow, so ANY
        per-bucket decrease means a process restarted and its counters
        reset to zero mid-interval.  Clamping each bucket at 0 (the old
        behavior) would keep the still-positive buckets and zero the
        reset ones — a partially-zeroed delta vector whose quantile is
        garbage, not conservative.  The whole interval is discarded
        (None, counted as ``autoscale_counter_resets``) and the next
        poll measures cleanly against the post-reset baseline."""
        total = [0] * (len(LATENCY_BUCKETS_MS) + 1)
        for route, r in (prom_snap.get("routes") or {}).items():
            if not _data_plane(route):
                continue
            for i, c in enumerate(
                    (r.get("latency_ms") or {}).get("buckets") or ()):
                total[i] += int(c)
        prev, self._prev_buckets = self._prev_buckets, total
        if prev is None:
            return None  # first poll: no interval yet
        if any(c < p for c, p in zip(total, prev)):
            self.counter_resets += 1
            if self.metrics is not None:
                self.metrics.inc("autoscale_counter_resets")
            _log.warning("counter reset detected (process restart?): "
                         "discarding this interval's p99")
            return None
        delta = [c - p for c, p in zip(total, prev)]
        return bucket_quantile(delta, 0.99)

    def poll_signals(self) -> Signals:
        s = Signals()
        try:
            m = self._fetch(f"{self.router_url}/metrics")
            prom = self._fetch(
                f"{self.router_url}/metrics?format=prometheus-json")
        except Exception as e:  # noqa: BLE001 — router unreachable
            _log.warning("router poll failed: %s", e)
            return s
        cluster = m.get("cluster") or {}
        membership = cluster.get("membership") or {}
        s.merged_of = int(membership.get("shards") or 0)
        groups: dict[int, int] = {sh: 0 for sh in range(s.merged_of)}
        replica_urls = []
        for r in (membership.get("replicas") or {}).values():
            if r.get("live") and r.get("ready") \
                    and int(r.get("of") or 0) == s.merged_of:
                sh = int(r.get("shard") or 0)
                groups[sh] = groups.get(sh, 0) + 1
                replica_urls.append(r.get("url"))
        s.group_sizes = groups
        qw = (cluster.get("scatter") or {}).get("cluster_queue_wait_ms")
        s.queue_wait_ms = None if qw is None else float(qw)
        # the router's SLO engine exports its worst fast-window burn as
        # a freshness gauge; absent (engine disabled) = no signal
        burn = (m.get("freshness") or {}).get("slo_burn_rate")
        s.slo_burn_rate = None if burn is None else float(burn)
        s.p99_ms = self._interval_p99(prom)
        if self.policy.update_lag_high_records > 0:
            lag = None
            for url in replica_urls:
                try:
                    rm = self._fetch(f"{url}/metrics", timeout=2.0)
                    v = (rm.get("freshness") or {}).get(
                        "update_lag_records")
                    if v is not None:
                        lag = float(v) if lag is None \
                            else max(lag, float(v))
                except Exception:  # noqa: BLE001 — replica scrape is
                    continue       # best-effort, like the router's
            s.update_lag_records = lag
        s.ok = s.merged_of >= 1
        return s

    # -- decision core -------------------------------------------------------

    def _gauges(self, s: Signals) -> None:
        if self.metrics is None:
            return
        self.metrics.set_gauge("autoscale_p99_ms",
                               -1.0 if s.p99_ms is None else
                               round(s.p99_ms, 1))
        self.metrics.set_gauge("autoscale_queue_wait_ms",
                               -1.0 if s.queue_wait_ms is None else
                               round(s.queue_wait_ms, 1))
        self.metrics.set_gauge("autoscale_update_lag_records",
                               -1.0 if s.update_lag_records is None
                               else s.update_lag_records)
        self.metrics.set_gauge("autoscale_slo_burn_rate",
                               -1.0 if s.slo_burn_rate is None
                               else round(s.slo_burn_rate, 2))
        self.metrics.set_gauge(
            "autoscale_members",
            sum(self.launcher.owned(s.merged_of).values())
            if s.merged_of else 0)

    def step(self, s: Signals, now: float | None = None) -> dict | None:
        """Advance streaks and maybe act; returns the action record
        ({kind, shard, member, reason}) or None."""
        now = self._clock() if now is None else now
        self._gauges(s)
        if not s.ok:
            # can't see the cluster: never act blind, never accrue
            # streaks from blindness
            self.up_streak = self.down_streak = 0
            return None
        if now < self.cooldown_until:
            # settling: a just-spawned member is still replaying the
            # update topic, and pressure measured before it can take
            # load must not pre-charge the next action
            self.up_streak = self.down_streak = 0
            return None
        pressure = self.policy.pressure(s)
        if pressure:
            self.up_streak += 1
            self.down_streak = 0
        elif self.policy.calm(s):
            self.down_streak += 1
            self.up_streak = 0
        else:
            self.up_streak = self.down_streak = 0
        action = None
        if self.up_streak >= self.policy.scale_up_after:
            action = self._scale_up(s, "; ".join(pressure))
        elif self.down_streak >= self.policy.scale_down_after:
            action = self._scale_down(s)
        if action is not None:
            self.cooldown_until = now + self.policy.cooldown_sec
            self.up_streak = self.down_streak = 0
            self.actions.append(action)
            _log.warning("autoscale action: %s", action)
        return action

    def _scale_up(self, s: Signals, reason: str) -> dict | None:
        # thinnest group first (HA before raw capacity), lowest shard
        # id as the deterministic tie-break
        eligible = [sh for sh in range(s.merged_of)
                    if s.group_sizes.get(sh, 0)
                    < self.policy.max_replicas_per_shard]
        if not eligible:
            _log.info("pressure (%s) but every group is at "
                      "max-replicas-per-shard", reason)
            return None
        shard = min(eligible,
                    key=lambda sh: (s.group_sizes.get(sh, 0), sh))
        member = self.launcher.spawn(shard, s.merged_of)
        return {"kind": "spawn", "shard": shard, "member": member,
                "reason": reason}

    def _scale_down(self, s: Signals) -> dict | None:
        owned = self.launcher.owned(s.merged_of)
        # retire from the fattest group, and only where the LIVE group
        # (not just our own members) stays >= the floor
        eligible = [sh for sh, n in owned.items()
                    if n > 0 and s.group_sizes.get(sh, 0)
                    > self.policy.min_replicas_per_shard]
        if not eligible:
            return None
        shard = max(eligible,
                    key=lambda sh: (s.group_sizes.get(sh, 0), -sh))
        member = self.launcher.retire(shard, s.merged_of)
        if member is None:
            return None
        return {"kind": "retire", "shard": shard, "member": member,
                "reason": f"calm x{self.policy.scale_down_after}"}

    # -- loop ----------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.step(self.poll_signals())
            except Exception:  # noqa: BLE001 — the supervisor must
                _log.exception("autoscale poll failed")  # outlive polls
            clockmod.wait(stop, self.poll_interval_sec)


def run_autoscaler(config, conf_path: str | None,
                   stop: threading.Event | None = None) -> int:
    """The ``autoscale`` subcommand body: build the launcher from the
    operator's conf, serve the autoscaler's own gauges on the obs
    side door when configured, poll until interrupted."""
    import tempfile

    from ..lambda_rt.metrics import MetricsRegistry
    from ..obs.server import ObsServer

    c = "oryx.cluster.autoscale"
    router_url = config.get_string(f"{c}.router-url")
    work_dir = config.get_optional_string(f"{c}.work-dir") \
        or tempfile.mkdtemp(prefix="oryx-autoscale-")
    base_conf = ""
    if conf_path:
        with open(conf_path, encoding="utf-8") as f:
            base_conf = f.read()
    metrics = MetricsRegistry()
    obs = ObsServer(config, metrics, tracer=None)
    obs.start()
    launcher = ProcessReplicaLauncher(config, base_conf, work_dir)
    scaler = Autoscaler(
        AutoscalePolicy.from_config(config), launcher, router_url,
        poll_interval_sec=config.get_int(
            f"{c}.poll-interval-ms") / 1000.0,
        metrics=metrics)
    stop = stop or threading.Event()
    _log.info("autoscaling %s (work dir %s)", router_url, work_dir)
    try:
        scaler.run(stop)
    except KeyboardInterrupt:
        pass
    finally:
        launcher.close()
        obs.close()
    return 0
