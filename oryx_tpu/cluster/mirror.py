"""Cross-region update-topic mirroring: the active-active fabric.

The lambda architecture's single source of truth is the update topic —
MODEL/MODEL-REF/UP in one totally ordered log — so geo-distribution
needs exactly one new moving part: a **mirror** process per inbound
link that tails a *source* region's update topic and replays it into
the *destination* region's topic (``python -m oryx_tpu mirror``,
supervised like every other role).  Each region then runs its own
router + replica fleet + speed layer over its own topics and serves
every read locally; fold-in writes converge through the mirror by the
same replay-convergence argument the speed layer already passes —
identical UP records applied to identical starting state yield
identical factors, whatever the interleaving of disjoint ids.

Exactly-once-effective replay
-----------------------------

Kafka gives at-least-once; a mirrored fold-in applied twice is
harmless only while UP records stay idempotent SETs, and a mirrored
record bounced back through the opposite mirror would loop forever.
Three mechanisms make the replay exactly-once-effective:

- **Origin headers.**  Every mirrored record carries ``origin-region``
  / ``origin-partition`` / ``origin-offset`` Kafka record headers (the
  PR 5 header machinery, kafka/api.py).  A record that already carries
  them (multi-hop topologies) keeps them untouched: a record's
  identity is where it was *born*, not the link it arrived on.
- **Loop prevention.**  A record whose ``origin-region`` names the
  destination region is dropped (``mirror_loop_drops``): with mirrors
  A⇄B, A's records reach B, but B's copy of them never re-enters A.
  Replica heartbeats (``HB``) are control plane for their own region's
  router — a foreign region cannot route to them — and are dropped
  too (``mirror_heartbeat_drops``).
- **The checkpoint + dedup fence.**  The mirror checkpoints a durable
  high-watermark per (origin, partition) in the store
  (``checkpoint.json`` under ``checkpoint-dir``, atomic tmp+rename —
  the same shape as the batch layer's ``_recover_offsets``), written
  AFTER each replayed batch.  A crash between the replay and the
  checkpoint therefore re-reads already-replayed records on restart —
  the classic at-least-once window — so recovery additionally scans
  the DESTINATION topic from the checkpoint's ``dest_scanned`` marks
  and advances each (origin, partition) watermark past every mirrored
  record actually found there: the durable destination log itself is
  the arbiter of what landed, exactly as the batch layer's generation
  files are for input offsets.  Re-read records at or below the fence
  are skipped (``mirror_dedup_skips``) — duplicated fold-in *effects*
  are impossible even though duplicated *reads* are not.

Bounded, measured staleness
---------------------------

``mirror_lag_records`` (source head minus replayed position) and
``cross_region_staleness_ms`` are exported on the mirror's side-door
ObsServer.  Staleness is measured, not modeled: every UP record the
speed layer publishes carries a ``ts`` header (publish wall-clock
epoch ms — the PR 5 stamp), so a drained batch yields an exact
record-age sample; between drains the gauge is the time since the
mirror last *confirmed* it was caught up, which keeps climbing through
a partitioned link (the poll seam ``mirror-link-partition``) exactly
when a bound is needed.  Registered as an ``oryx.obs.slo`` objective
of ``kind = "gauge"`` the staleness bound becomes a burn-rate alert:
pages fire while a region falls behind, not after users notice.

Failover is re-pointing clients: each region's router answers
``/admin/region`` with its identity, and docs/SCALING.md
"Multi-region" carries the runbook.  Chaos proof:
tests/test_region_it.py.
"""

from __future__ import annotations

import json
import logging
import threading

from ..common import clock as clockmod
from ..common import store
from ..common.config import Config
from ..kafka import utils as kafka_utils
from ..kafka.api import KeyMessage
from ..kafka.inproc import InProcTopicProducer, resolve_broker
from ..lambda_rt.metrics import MetricsRegistry
from ..obs import (engine_from_config, flight_from_config,
                   tracer_from_config)
from ..obs.server import ObsServer
from ..resilience import faults
from ..resilience.policy import (CircuitBreaker, ResilientTopicProducer,
                                 Retry)
from .membership import KEY_HEARTBEAT

_log = logging.getLogger(__name__)

__all__ = ["MirrorLayer", "MirrorCheckpoint",
            "H_ORIGIN_REGION", "H_ORIGIN_PARTITION", "H_ORIGIN_OFFSET"]

# record headers carried by every mirrored record (kafka/api.py):
# where the record was BORN — preserved untouched across further hops,
# so (origin-region, origin-partition, origin-offset) is a globally
# unique record identity whatever path it travelled
H_ORIGIN_REGION = "origin-region"
H_ORIGIN_PARTITION = "origin-partition"
H_ORIGIN_OFFSET = "origin-offset"


def origin_of(km: KeyMessage, source_region: str,
              partition: int, offset: int) -> tuple[str, int, int]:
    """A record's birth coordinates: its own origin headers when it was
    already mirrored once, else (source region, partition, offset) —
    the position the mirror read it at."""
    h = km.headers or {}
    try:
        if H_ORIGIN_REGION in h:
            return (str(h[H_ORIGIN_REGION]),
                    int(h.get(H_ORIGIN_PARTITION, 0)),
                    int(h[H_ORIGIN_OFFSET]))
    except (TypeError, ValueError, KeyError):
        pass  # malformed origin headers: treat as born at the source
    return source_region, partition, offset


class MirrorCheckpoint:
    """The mirror's durable state, one JSON document in the store
    (URI-capable via common/store, so a gs://-backed deployment works
    the same as a local directory):

    - ``source``: next source-topic offset to read, per partition —
      where the tail resumes;
    - ``watermarks``: highest ``origin-offset`` replayed into the
      destination, per ``"origin|partition"`` — the dedup fence;
    - ``dest_scanned``: destination-topic offsets already examined by
      recovery, per partition — the next recovery scan is incremental.

    Written atomically (tmp + rename) after each replayed batch.  A
    crash between a batch's sends and its checkpoint write loses only
    the in-memory watermark advance; :meth:`recover` re-derives it from
    the destination log itself (see the module docstring)."""

    FILE = "mirror-checkpoint.json"

    def __init__(self, checkpoint_dir: str):
        store.mkdirs(checkpoint_dir)
        self.path = store.join(checkpoint_dir, self.FILE)
        self.source: dict[int, int] = {}
        self.watermarks: dict[tuple[str, int], int] = {}
        self.dest_scanned: dict[int, int] = {}
        self.load()

    def load(self) -> None:
        if not store.exists(self.path):
            return
        try:
            with store.open_read(self.path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            _log.warning("Unreadable mirror checkpoint at %s; recovery "
                         "will re-derive the fence from the destination "
                         "log", self.path, exc_info=True)
            return
        self.source = {int(k): int(v)
                       for k, v in (doc.get("source") or {}).items()}
        self.dest_scanned = {int(k): int(v) for k, v
                             in (doc.get("dest_scanned") or {}).items()}
        self.watermarks = {}
        for k, v in (doc.get("watermarks") or {}).items():
            region, _, part = k.rpartition("|")
            self.watermarks[(region, int(part))] = int(v)

    def save(self) -> None:
        doc = {
            "source": {str(k): v for k, v in self.source.items()},
            "watermarks": {f"{r}|{p}": v
                           for (r, p), v in self.watermarks.items()},
            "dest_scanned": {str(k): v
                             for k, v in self.dest_scanned.items()},
        }
        tmp = self.path + ".tmp"
        with store.open_write(tmp, "wb") as f:
            f.write(json.dumps(doc, sort_keys=True).encode("utf-8"))
        store.rename(tmp, self.path)

    # -- the fence -----------------------------------------------------------

    def behind_fence(self, origin: str, partition: int,
                     offset: int) -> bool:
        wm = self.watermarks.get((origin, partition))
        return wm is not None and offset <= wm

    def advance_fence(self, origin: str, partition: int,
                      offset: int) -> None:
        key = (origin, partition)
        if offset > self.watermarks.get(key, -1):
            self.watermarks[key] = offset


class MirrorLayer:
    """start()/await_()/close() around the replay loop — the same
    lifecycle contract as the other layers, so ``python -m oryx_tpu
    mirror`` runs supervised (deploy/main.py)."""

    def __init__(self, config: Config,
                 clock: clockmod.Clock | None = None):
        self.config = config
        # the injectable clock seam: the deterministic cluster
        # simulation (oryx_tpu/sim) drives a MirrorLayer under virtual
        # time, and the staleness-gauge tests pin their windows on a
        # ManualClock instead of racing real-sleep margins
        self._clock = clock if clock is not None else clockmod.get()
        r = "oryx.cluster.region"
        self.region = config.get_optional_string(f"{r}.name")
        if not self.region:
            raise ValueError(
                "mirror requires oryx.cluster.region.name — the "
                "destination region's identity (loop prevention keys "
                "on it)")
        m = f"{r}.mirror"
        self.source_broker = config.get_optional_string(
            f"{m}.source-broker")
        if not self.source_broker:
            raise ValueError(
                "mirror requires oryx.cluster.region.mirror."
                "source-broker — the remote region's update topic")
        self.source_topic = config.get_optional_string(
            f"{m}.source-topic") or config.get_string(
            "oryx.update-topic.message.topic")
        self.source_region = config.get_string(f"{m}.source-region")
        checkpoint_dir = config.get_optional_string(
            f"{m}.checkpoint-dir")
        if not checkpoint_dir:
            raise ValueError(
                "mirror requires oryx.cluster.region.mirror."
                "checkpoint-dir — the durable high-watermark store the "
                "exactly-once-effective fence lives in")
        self.poll_interval_sec = config.get_int(
            f"{m}.poll-interval-ms") / 1000.0
        self.max_batch_records = config.get_int(
            f"{m}.max-batch-records")
        self.dest_broker = config.get_string("oryx.update-topic.broker")
        self.dest_topic = config.get_string(
            "oryx.update-topic.message.topic")
        if (self.source_broker == self.dest_broker
                and self.source_topic == self.dest_topic):
            raise ValueError(
                "mirror source and destination are the same topic — "
                "a self-mirror would double every record")
        faults.configure_from_config(config)
        self.checkpoint = MirrorCheckpoint(checkpoint_dir)
        # replay sends run behind retry + breaker (the PR 1 policies):
        # a transient destination-broker failure retries with backoff,
        # a sustained one opens the breaker and the loop backs off
        # without losing its position — nothing is checkpointed past
        # an unsent record
        self._producer = ResilientTopicProducer(
            InProcTopicProducer(self.dest_broker, self.dest_topic),
            retry=Retry.from_config("mirror-replay", config),
            breaker=CircuitBreaker.from_config("mirror-replay-dest",
                                               config))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # staleness clocks (single-writer loop thread, many readers —
        # plain attribute stores are atomic in CPython).  Seeded at
        # construction: a mirror that has NEVER confirmed sync (e.g.
        # started into an already-partitioned link) must report
        # staleness climbing from its start, not a forever-0
        self._caught_up_mono: float = self._clock.monotonic()
        # None until the source head has been OBSERVED at least once: a
        # mirror restarted into a dead link must report unknown (null),
        # never a constructor-seeded 0 that reads as "caught up"
        self._last_lag: int | None = None
        self._last_batch_staleness_ms: int | None = None
        self.link_failures = 0
        self.metrics = MetricsRegistry()
        self.metrics.gauge_fn("mirror_lag_records", self._lag_gauge)
        self.metrics.gauge_fn("cross_region_staleness_ms",
                              self._staleness_gauge)
        self.tracer = tracer_from_config(config, "mirror")
        # the staleness bound as a burn-rate alert: register a
        # kind="gauge" objective over cross_region_staleness_ms under
        # oryx.obs.slo.objectives.* and pages fire while the region
        # falls behind (obs/slo.py)
        self.slo_engine = engine_from_config(config, self.metrics)
        if self.slo_engine is not None:
            self.metrics.gauge_fn("slo_burn_rate",
                                  self.slo_engine.burn_gauge)
            self.metrics.gauge_fn("slo_error_budget_remaining",
                                  self.slo_engine.budget_gauge)
        # flight recorder (obs/flight.py; None until the config gate
        # opens): a staleness page or link-fault in this region leaves
        # a bundle on the mirror's own side door
        self.flight = flight_from_config(config, "mirror", self.metrics,
                                         slo=self.slo_engine)
        if self.flight is not None and self.slo_engine is not None:
            flight = self.flight
            self.slo_engine.on_page = lambda name, st: flight.trigger(
                "slo-page", {"objective": name,
                             "burn_5m": st.get("burn_5m")})
        self.obs_server = ObsServer(config, self.metrics, self.tracer,
                                    extra_context={
                                        "region_info": self.status,
                                        # /admin/slo serves the
                                        # staleness objective's alert
                                        # state on the same side door
                                        "slo": self.slo_engine,
                                        "flight": self.flight})

    # -- gauges --------------------------------------------------------------

    def _lag_gauge(self) -> int | None:
        """Source head minus replayed position.  Reads the source
        broker directly (like obs/freshness.topic_lag_fn); when the
        link is down the LAST OBSERVED lag is held instead of
        reporting nothing, and a mirror that has never reached the
        source at all reports None (unknown) — a partition, or a
        restart into one, must never read as 'caught up'."""
        try:
            latest = resolve_broker(self.source_broker).latest_offsets(
                self.source_topic)
            self._last_lag = sum(
                max(0, e - self.checkpoint.source.get(p, 0))
                for p, e in enumerate(latest))
        except Exception:  # noqa: BLE001 — link down: hold last value
            pass
        return self._last_lag

    def _staleness_gauge(self) -> int:
        """Milliseconds the destination region may be behind the
        source.  When the last drained batch carried ``ts`` headers the
        base is that batch's exact worst record age (measured, not
        modeled); on top of it rides the time since the mirror last
        CONFIRMED it was caught up — which keeps climbing through a
        partitioned link, when no measurement can arrive at all (the
        clock is seeded at construction, so a mirror started INTO a
        partition climbs from its start)."""
        since_sync = int(
            (self._clock.monotonic() - self._caught_up_mono) * 1000)
        base = self._last_batch_staleness_ms or 0
        return base + since_sync

    def status(self) -> dict:
        """The /admin/region block on the mirror's ObsServer."""
        return {
            "role": "mirror",
            "source_region": self.source_region,
            "source_broker": self.source_broker,
            "source_topic": self.source_topic,
            "dest_topic": self.dest_topic,
            "link_failures": self.link_failures,
            "source_positions": dict(self.checkpoint.source),
            "watermarks": {f"{r}|{p}": v for (r, p), v
                           in sorted(self.checkpoint.watermarks.items())},
        }

    # -- recovery ------------------------------------------------------------

    def recover(self) -> int:
        """Finish an interrupted replay's bookkeeping: scan the
        DESTINATION topic from the checkpoint's ``dest_scanned`` marks
        and advance every (origin, partition) watermark past the
        mirrored records actually found — sends that landed after the
        last checkpoint write (the crash window) re-enter the fence.
        Never rewinds; a clean shutdown's scan is a no-op.  Returns the
        number of mirrored records examined."""
        broker = resolve_broker(self.dest_broker)
        kafka_utils.maybe_create_topic(self.dest_broker, self.dest_topic)
        ends = broker.latest_offsets(self.dest_topic)
        starts = [self.checkpoint.dest_scanned.get(p, 0)
                  for p in range(len(ends))]
        examined = 0
        for km in broker.read_ranges(self.dest_topic, starts, ends):
            h = km.headers or {}
            if H_ORIGIN_REGION not in h:
                continue  # locally-born record: not mirror bookkeeping
            try:
                self.checkpoint.advance_fence(
                    str(h[H_ORIGIN_REGION]),
                    int(h.get(H_ORIGIN_PARTITION, 0)),
                    int(h[H_ORIGIN_OFFSET]))
                examined += 1
            except (TypeError, ValueError):
                continue  # malformed headers: not fence material
        for p, e in enumerate(ends):
            self.checkpoint.dest_scanned[p] = max(
                self.checkpoint.dest_scanned.get(p, 0), e)
        if examined:
            _log.info("Mirror recovery advanced the dedup fence over "
                      "%d mirrored record(s) found in the destination "
                      "log", examined)
        self.checkpoint.save()
        return examined

    # -- the replay ----------------------------------------------------------

    def _replay_one(self, km: KeyMessage, partition: int,
                    offset: int) -> bool:
        """Classify and (maybe) replay one source record; returns True
        when it was sent to the destination."""
        if km.key == KEY_HEARTBEAT:
            # a foreign fleet's heartbeats would pollute the local
            # router's membership with unreachable URLs
            self.metrics.inc("mirror_heartbeat_drops")
            return False
        origin, o_part, o_off = origin_of(km, self.source_region,
                                          partition, offset)
        if origin == self.region:
            # loop prevention: this record was born HERE and came back
            # through the opposite mirror — A⇄B must never ping-pong
            self.metrics.inc("mirror_loop_drops")
            return False
        if self.checkpoint.behind_fence(origin, o_part, o_off):
            # the dedup fence: a crash between replay and checkpoint
            # re-reads records the destination log already holds
            self.metrics.inc("mirror_dedup_skips")
            return False
        headers = dict(km.headers or {})
        # write the COMPUTED birth coordinates: origin_of already
        # preserved valid existing headers, and overwriting normalizes
        # a malformed set (which fell back to source coordinates) into
        # something the fence can key on
        headers[H_ORIGIN_REGION] = origin
        headers[H_ORIGIN_PARTITION] = str(o_part)
        headers[H_ORIGIN_OFFSET] = str(o_off)
        self._producer.send(km.key, km.message, headers=headers)
        self.checkpoint.advance_fence(origin, o_part, o_off)
        self.metrics.inc("mirror_records_replayed")
        return True

    def poll_once(self) -> int:
        """One micro-batch: read up to ``max_batch_records`` per source
        partition past the checkpoint, replay, then checkpoint.
        Returns the number of records replayed (not merely read).
        Raises on a dead link — the caller owns backoff."""
        # chaos seam: the inter-region link is partitioned — every
        # poll fails until the fault clears, and the staleness gauges
        # must climb the whole time (tests/test_region_it.py)
        faults.fire("mirror-link-partition",
                    error=lambda: ConnectionError(
                        "mirror link partitioned"))
        broker = resolve_broker(self.source_broker)
        ends = broker.latest_offsets(self.source_topic)
        starts, capped = [], []
        for p, e in enumerate(ends):
            s = self.checkpoint.source.get(p, 0)
            starts.append(s)
            capped.append(min(e, s + self.max_batch_records))
        if all(c <= s for s, c in zip(starts, capped)):
            # fully drained: stamp the caught-up confirmation the
            # staleness gauge measures from
            self._caught_up_mono = self._clock.monotonic()
            self._last_batch_staleness_ms = 0
            return 0
        replayed = 0
        oldest_ts: int | None = None
        t_drain = self._clock.time()
        # per-partition replay preserves each partition's record order
        # (Kafka's guarantee — all the convergence argument needs)
        for p in range(len(ends)):
            if capped[p] <= starts[p]:
                continue
            batch = broker.read_ranges(
                self.source_topic,
                [starts[i] if i == p else 0 for i in range(len(ends))],
                [capped[i] if i == p else 0 for i in range(len(ends))])
            for i, km in enumerate(batch):
                if self._replay_one(km, p, starts[p] + i):
                    replayed += 1
                    ts = (km.headers or {}).get("ts")
                    if ts is not None:
                        try:
                            t = int(ts)
                            if oldest_ts is None or t < oldest_ts:
                                oldest_ts = t
                        except (TypeError, ValueError):
                            pass
            self.checkpoint.source[p] = capped[p]
        if oldest_ts is not None:
            # exact measured staleness of this batch: how old its
            # oldest record (by the PR 5 `ts` stamp) was when it became
            # visible in the destination region
            self._last_batch_staleness_ms = max(
                0, int(t_drain * 1000) - oldest_ts)
        # chaos seam: die AFTER the batch's sends but BEFORE the
        # checkpoint write — the exact window the dedup fence exists
        # for (recovery must not duplicate a single fold-in effect)
        faults.fire("mirror-crash-mid-replay")
        # sends before this checkpoint are below the destination head:
        # the next recovery scan may start past them
        try:
            self.checkpoint.dest_scanned = {
                p: e for p, e in enumerate(
                    resolve_broker(self.dest_broker).latest_offsets(
                        self.dest_topic))}
        except Exception:  # noqa: BLE001 — scan mark is an optimization
            pass
        self.checkpoint.save()
        if all(self.checkpoint.source.get(p, 0) >= e
               for p, e in enumerate(ends)):
            self._caught_up_mono = self._clock.monotonic()
        return replayed

    def _loop(self) -> None:
        """Deterministic fixed-interval polling with per-failure
        accounting.  A failed poll (dead link, dest breaker open)
        counts, logs, and waits ONE poll interval — not a compounding
        backoff: the staleness gauge is the pressure valve, and a
        healed link must resume within one interval, bounded, so the
        chaos IT's heal-time is deterministic.  stop() interrupts any
        wait immediately (Event.wait)."""
        while not self._stop.is_set():
            try:
                drained = self.poll_once()
            except Exception:  # noqa: BLE001 — link down: hold position
                self.link_failures += 1
                self.metrics.inc("mirror_link_failures")
                if self.link_failures in (1, 10) \
                        or self.link_failures % 100 == 0:
                    _log.warning("mirror poll failed (%d so far); "
                                 "holding position, staleness climbing",
                                 self.link_failures, exc_info=True)
                self._clock.wait(self._stop, self.poll_interval_sec)
                continue
            if drained == 0:
                self._clock.wait(self._stop, self.poll_interval_sec)
            # a full batch replays again immediately: catch-up after a
            # healed partition must run at link speed, not poll speed

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        _log.info("Starting mirror %s -> %s (%s @ %s -> %s @ %s)",
                  self.source_region, self.region, self.source_topic,
                  self.source_broker, self.dest_topic, self.dest_broker)
        self.obs_server.start()
        self.recover()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="MirrorReplay")
        self._thread.start()

    def await_(self) -> None:
        while self._thread and self._thread.is_alive():
            self._thread.join(1.0)

    def close(self) -> None:
        self._stop.set()
        if self.flight is not None:
            self.flight.close()
        self.obs_server.close()
        if self._thread:
            self._thread.join(10.0)
        try:
            self.checkpoint.save()
        except Exception:  # noqa: BLE001 — best-effort final flush
            _log.exception("mirror checkpoint flush on close failed")
        self._producer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
