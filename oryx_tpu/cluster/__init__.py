"""Horizontally-sharded serving cluster (scale-OUT, not just scale-up).

``parallel/serving_dist.py`` shards the item scan over the devices of
ONE host; this package shards the item *catalog* over serving
processes, so both capacity and sustained qps scale with replica count
(the reference runs N full-model instances behind a dumb load balancer
— SURVEY serving-layer notes; here each replica holds 1/N of the
catalog and the gateway merges exactly).

Pieces:

- :mod:`.sharding` — stable item-id -> shard hash (the Kafka
  partitioner contract, kafka/partitioner.py).
- :mod:`.membership` — replica heartbeats on the update topic
  (``HB`` key, riding next to MODEL/UP) and the router's live,
  generation-aware registry built from them.
- :mod:`.shard_resources` — the replica-internal HTTP surface
  (``/shard/recommend`` and friends) answering exact local top-k with
  merge ordinals.
- :mod:`.merge` — the exact global top-N merge with the cluster's
  canonical tie-break.
- :mod:`.scatter` — deadline-propagating, hedging, circuit-broken
  fan-out client.
- :mod:`.router` — the gateway layer: the existing public HTTP front
  end, answered by scatter-gather over the shard replicas, degrading
  to partial answers (``X-Oryx-Partial``) when shards are down.
- :mod:`.admission` — measured-queue-wait admission control: overload
  sheds data-plane requests as fast 503 + ``Retry-After`` instead of
  queueing into collapse.
- :mod:`.autoscaler` — the gauge-driven supervisor
  (``python -m oryx_tpu autoscale``): consumes the router's own
  signals (merged p99 buckets, queue wait, update lag) and
  spawns/retires replica-group members under the resilience
  Supervisor.

Run a 2-shard cluster (R-way replica groups = start R processes per
shard; any subset of a shard's group covers it)::

    python -m oryx_tpu serving --shard 0/2 --conf my.conf &
    python -m oryx_tpu serving --shard 1/2 --conf my.conf &
    python -m oryx_tpu router --conf my.conf &

Live N→M reshard (no restarts anywhere): declare the target
(``POST /admin/topology {"of": M}``), start the M-way fleet, watch
``GET /admin/topology`` until cutover, retire the old fleet.

See docs/SCALING.md for the topology, protocol, and runbooks.
"""
