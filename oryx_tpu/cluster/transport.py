"""Multiplexed framed router→replica transport: one persistent
connection per replica carrying interleaved request streams.

The legacy internal hop (cluster/scatter.py) is HTTP/1.1 over a
per-URL socket pool: every concurrently outstanding request to a
replica pins one socket, a hedge costs a TCP connect when the pool is
empty, and cancelling a losing attempt means abandoning a socket
mid-response.  This module replaces the hop with a length-prefixed
frame protocol over ONE connection per replica:

- **streams** — every request gets a per-connection stream id;
  responses come back in completion order and are demultiplexed by id,
  so a slow response never head-of-line-blocks its poolmates;
- **hedges cost a frame** — a hedged attempt is one more REQ frame on
  the sibling's existing connection, not a connect;
- **cancellation is explicit** — a losing hedge (or an expired
  deadline) sends a CANCEL frame; the replica skips the work if it has
  not started and drops the response if it has, and the connection
  stays healthy for every other stream;
- **deadline propagation** — the REQ header carries the request's
  remaining budget exactly as ``X-Deadline-Ms`` does on the HTTP hop.

Wire format (all integers big-endian)::

    frame   := u32 length | u8 type | u32 stream_id | payload
    REQ(1)  := u32 hlen | header-JSON | body          (router → replica)
    RESP(2) := u32 hlen | header-JSON | body          (replica → router)
    CANCEL(3) (empty payload)                         (router → replica)
    AUTH(4) := JSON {"ha1": md5(user:realm:password)} (router → replica)

REQ header-JSON: ``{"m": method, "p": path, "h": {headers}}``; RESP
header-JSON: ``{"s": status, "h": {lower-cased response headers}}``.
The replica answers frames through the SAME HttpApp dispatcher the
``/shard/*`` HTTP resources run on (a buffered handler adapter), so a
framed answer is byte-identical to the HTTP hop's by construction —
and the dispatcher consults the replica-side result cache
(cluster/result_cache.py ShardResultCache) first, so a repeated shard
query under an unchanged model epoch skips the device entirely.

Trust model: the framed hop is cluster-internal cleartext TCP.  When
DIGEST credentials are configured (``oryx.serving.api.user-name``) the
first frame on a connection must be an AUTH frame carrying the same
HA1 the DIGEST scheme stores; a mismatch closes the connection.
Deployments that require TLS on the internal hop keep
``oryx.cluster.transport.enabled = false`` — the HTTP/1.1 pool remains
the fallback and the default.

Chaos seam: ``transport-frame-stall`` stalls ONE stream's response
write on the replica (mode=delay) — the chaos proof that its
connection-mates keep flowing and the router's hedge fires a frame,
not a connect.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import socket
import struct
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from queue import Empty, SimpleQueue

from ..common import clock as clockmod
from ..resilience import faults

_log = logging.getLogger(__name__)

__all__ = ["FrameTransport", "FrameServer", "StreamAbandoned",
           "FRAME_REQ", "FRAME_RESP", "FRAME_CANCEL", "FRAME_AUTH",
           "read_frame", "write_frame"]

FRAME_REQ = 1
FRAME_RESP = 2
FRAME_CANCEL = 3
FRAME_AUTH = 4

# u32 length | u8 type | u32 stream
_HEAD = struct.Struct(">IBI")
# a frame larger than this is protocol abuse or corruption, not data
_MAX_FRAME = 64 << 20


class StreamAbandoned(Exception):
    """This stream was cancelled locally (a hedge sibling won, or the
    deadline expired) — not a replica failure and never breaker
    evidence."""


def write_frame(sock: socket.socket, ftype: int, stream: int,
                payload: bytes, lock: threading.Lock) -> None:
    """One frame, atomically with respect to other writers on the same
    connection (the whole point of the per-connection write lock: an
    interleaved half-frame would desync every stream at once)."""
    head = _HEAD.pack(5 + len(payload), ftype, stream)
    with lock:
        sock.sendall(head + payload)


def read_frame(rfile) -> tuple[int, int, bytes]:
    """(type, stream, payload); raises ConnectionError at EOF or on a
    malformed/oversized frame."""
    head = rfile.read(_HEAD.size)
    if not head:
        raise ConnectionError("frame connection closed")
    while len(head) < _HEAD.size:
        more = rfile.read(_HEAD.size - len(head))
        if not more:
            raise ConnectionError("truncated frame head")
        head += more
    length, ftype, stream = _HEAD.unpack(head)
    if length < 5 or length > _MAX_FRAME:
        raise ConnectionError(f"bad frame length {length}")
    need = length - 5
    chunks = []
    while need:
        got = rfile.read(need)
        if not got:
            raise ConnectionError("truncated frame payload")
        chunks.append(got)
        need -= len(got)
    return ftype, stream, b"".join(chunks)


def _pack_msg(header: dict, body: bytes) -> bytes:
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return struct.pack(">I", len(hj)) + hj + body


def _unpack_msg(payload: bytes) -> tuple[dict, bytes]:
    (hlen,) = struct.unpack_from(">I", payload)
    header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    return header, payload[4 + hlen:]


def auth_ha1(user: str, password: str, realm: str = "Oryx") -> str:
    """The DIGEST scheme's HA1 — the shared secret both ends of the
    framed hop already hold (lambda_rt/http.py `_auth_ok`)."""
    return hashlib.md5(
        f"{user}:{realm}:{password or ''}".encode()).hexdigest()


# -- client (router side) -----------------------------------------------------

# posted into a stream's box when the stream is cancelled locally
_ABANDON = object()


class _ClientConn:
    """One multiplexed connection: a writer-locked socket, a reader
    thread demuxing RESP frames into per-stream boxes."""

    def __init__(self, addr: tuple[str, int], connect_timeout: float,
                 ha1: str | None):
        self.sock = socket.create_connection(addr,
                                             timeout=connect_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._rfile = self.sock.makefile("rb")
        self.wlock = threading.Lock()
        self._lock = threading.Lock()
        self._streams: dict[int, SimpleQueue] = {}
        self._next = 0
        self.dead = False
        self.last_used = clockmod.monotonic()
        if ha1 is not None:
            write_frame(self.sock, FRAME_AUTH, 0,
                        json.dumps({"ha1": ha1}).encode(), self.wlock)
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name="transport-reader")
        self._reader.start()

    def open_stream(self) -> tuple[int, SimpleQueue]:
        with self._lock:
            if self.dead:
                raise ConnectionError("frame connection dead")
            self._next += 1
            box: SimpleQueue = SimpleQueue()
            self._streams[self._next] = box
            return self._next, box

    def close_stream(self, stream: int) -> None:
        with self._lock:
            self._streams.pop(stream, None)

    def abandon_stream(self, stream: int) -> bool:
        """Wake the stream's waiter with the abandoned sentinel and
        send a CANCEL frame (best-effort).  True when the stream was
        still open."""
        with self._lock:
            box = self._streams.pop(stream, None)
        if box is None:
            return False
        box.put(_ABANDON)
        try:
            write_frame(self.sock, FRAME_CANCEL, stream, b"",
                        self.wlock)
        except OSError:
            pass
        return True

    @property
    def in_flight(self) -> int:
        with self._lock:
            return len(self._streams)

    def _read_loop(self) -> None:
        try:
            while True:
                ftype, stream, payload = read_frame(self._rfile)
                if ftype != FRAME_RESP:
                    continue  # unknown server frame: ignore, stay up
                with self._lock:
                    box = self._streams.pop(stream, None)
                if box is not None:
                    box.put(payload)
        except (OSError, ConnectionError, ValueError):
            pass
        finally:
            self.kill()

    def kill(self) -> None:
        with self._lock:
            if self.dead:
                return
            self.dead = True
            streams = list(self._streams.values())
            self._streams.clear()
        for box in streams:
            box.put(ConnectionError("frame connection died"))
        try:
            self.sock.close()
        except OSError:
            pass


class FrameTransport:
    """Router-side framed client: one :class:`_ClientConn` per replica
    transport address, idle connections aged out with the same TTL
    policy the scatter pool uses (autoscaler churn on ephemeral ports
    must not grow the map forever)."""

    def __init__(self, config):
        c = "oryx.cluster.transport"
        self.connect_timeout = \
            config.get_int(f"{c}.connect-timeout-ms") / 1000.0
        self.idle_ttl_sec = config.get_int(f"{c}.idle-ttl-ms") / 1000.0
        user = config.get_optional_string("oryx.serving.api.user-name")
        self._ha1 = auth_ha1(user, config.get_optional_string(
            "oryx.serving.api.password")) if user else None
        self._conns: dict[tuple[str, int], _ClientConn] = {}
        self._lock = threading.Lock()
        self._last_sweep = clockmod.monotonic()
        # operator counters (surfaced through ScatterGather.stats)
        self.cancels_sent = 0
        self.reconnects = 0

    # -- connection map ------------------------------------------------------

    def _addr_of(self, hb) -> tuple[str, int]:
        host = urllib.parse.urlparse(hb.url).hostname
        return (host, int(hb.tport))

    def _acquire(self, addr: tuple[str, int]
                 ) -> tuple[_ClientConn, bool]:
        """(connection, reused) — reused means from the map, which may
        have died since its last frame (replica restart)."""
        self._sweep()
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.dead:
                conn.last_used = clockmod.monotonic()
                return conn, True
        fresh = _ClientConn(addr, self.connect_timeout, self._ha1)
        with self._lock:
            cur = self._conns.get(addr)
            if cur is not None and not cur.dead:
                # lost the connect race: ride the winner, drop ours
                fresh.kill()
                cur.last_used = clockmod.monotonic()
                return cur, True
            if cur is not None:
                self.reconnects += 1
            self._conns[addr] = fresh
        return fresh, False

    def _drop(self, addr: tuple[str, int], conn: _ClientConn) -> None:
        with self._lock:
            if self._conns.get(addr) is conn:
                del self._conns[addr]
        conn.kill()

    def _sweep(self) -> None:
        """Age out idle connections — the same eviction the scatter
        pool applies: a retired replica's ephemeral port must not pin
        a socket (and a map entry) forever."""
        now = clockmod.monotonic()
        if now - self._last_sweep < max(1.0, self.idle_ttl_sec / 4):
            return
        with self._lock:
            self._last_sweep = now
            stale = [(a, c) for a, c in self._conns.items()
                     if c.dead or (c.in_flight == 0
                                   and now - c.last_used
                                   > self.idle_ttl_sec)]
            for addr, _ in stale:
                del self._conns[addr]
        for _, conn in stale:
            conn.kill()

    def open_connections(self) -> int:
        with self._lock:
            return sum(1 for c in self._conns.values() if not c.dead)

    def connection_snapshot(self) -> dict:
        """addr -> in-flight stream count, for /metrics and the bench's
        sockets-per-replica evidence."""
        with self._lock:
            return {f"{a[0]}:{a[1]}": c.in_flight
                    for a, c in self._conns.items() if not c.dead}

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.kill()

    # -- one round trip ------------------------------------------------------

    def request(self, hb, method: str, path: str, body: bytes | None,
                headers: dict[str, str], timeout: float,
                cancel=None) -> tuple[int, bytes, dict[str, str]]:
        """One framed request against ``hb``'s transport listener.
        Mirrors the HTTP hop's contract: (status, body bytes,
        lower-cased response headers); ConnectionError on transport
        death (retried once internally when the cached connection was
        stale — the replica-restart case); TimeoutError when the
        window expires (the stream is CANCELled); StreamAbandoned when
        ``cancel`` fired (a hedge sibling won)."""
        addr = self._addr_of(hb)
        conn, reused = self._acquire(addr)
        try:
            return self._roundtrip(conn, method, path, body, headers,
                                   timeout, cancel)
        except ConnectionError:
            self._drop(addr, conn)
            if not reused:
                raise
            # stale cached connection: the replica restarted between
            # frames — a property of THIS connection, not the replica.
            # Internal queries are idempotent reads; retry once fresh.
            conn, _ = self._acquire(addr)
            try:
                return self._roundtrip(conn, method, path, body,
                                       headers, timeout, cancel)
            except ConnectionError:
                self._drop(addr, conn)
                raise

    def _roundtrip(self, conn: _ClientConn, method: str, path: str,
                   body: bytes | None, headers: dict[str, str],
                   timeout: float, cancel) -> tuple[int, bytes, dict]:
        stream, box = conn.open_stream()
        registered = None
        if cancel is not None:
            registered = cancel.register(
                lambda: self._abandon(conn, stream))
            if registered is None:
                # the race was already lost before the frame went out
                conn.close_stream(stream)
                raise StreamAbandoned("cancelled before send")
        try:
            payload = _pack_msg({"m": method, "p": path, "h": headers},
                                body or b"")
            write_frame(conn.sock, FRAME_REQ, stream, payload,
                        conn.wlock)
            try:
                got = box.get(timeout=max(0.001, timeout))
            except Empty:
                # the window expired: tell the replica to stop — the
                # cancellation that used to mean an abandoned socket
                # is now one frame on a healthy connection
                if conn.abandon_stream(stream):
                    with self._lock:
                        self.cancels_sent += 1
                raise TimeoutError(
                    f"frame stream timed out after {timeout:.3f}s"
                ) from None
            if got is _ABANDON:
                with self._lock:
                    self.cancels_sent += 1
                raise StreamAbandoned("hedge sibling won")
            if isinstance(got, BaseException):
                raise got
            header, raw = _unpack_msg(got)
            rhdrs = {str(k).lower(): str(v)
                     for k, v in (header.get("h") or {}).items()}
            return int(header["s"]), raw, rhdrs
        finally:
            if registered is not None:
                cancel.unregister(registered)
            conn.close_stream(stream)
            conn.last_used = clockmod.monotonic()

    @staticmethod
    def _abandon(conn: _ClientConn, stream: int) -> None:
        conn.abandon_stream(stream)


# -- server (replica side) ----------------------------------------------------

class _FrameHandler:
    """The buffered handler adapter the frame dispatcher hands to
    HttpApp.handle — the exact surface the threaded server's handler
    exposes, with the response captured instead of written to a
    socket.  Framed requests dispatch through the SAME app (routes,
    metrics, tracing, deadline minting), so a framed answer is
    byte-identical to the HTTP hop's by construction."""

    def __init__(self, method: str, path: str, headers: dict[str, str],
                 body: bytes):
        self.command = method
        self.path = path
        self.headers = dict(headers)
        self.headers["Content-Length"] = str(len(body))
        self.rfile = io.BytesIO(body)
        self.wfile = io.BytesIO()
        self.status = 0
        self.resp_headers: dict[str, str] = {}
        self._close = False
        # connection-level AUTH already ran (FrameServer): skip the
        # per-request DIGEST dance the HTTP hop pays
        self._oryx_preauth = True

    def send_response(self, status: int) -> None:
        self.status = status

    def send_header(self, key: str, value: str) -> None:
        self.resp_headers[key] = str(value)

    def end_headers(self) -> None:
        pass


class FrameServer:
    """Replica-side frame listener: accepts the router's multiplexed
    connections, dispatches REQ frames through the serving layer's
    HttpApp on a bounded worker pool, honors CANCEL, and consults the
    replica-side result cache before touching the device."""

    def __init__(self, app, config, metrics=None, shard_cache=None,
                 port: int | None = None):
        c = "oryx.cluster.transport"
        self.app = app
        self.metrics = metrics
        self.shard_cache = shard_cache
        self._workers = ThreadPoolExecutor(
            max_workers=max(1, config.get_int(f"{c}.workers")),
            thread_name_prefix="frame-serve")
        self._require_ha1 = None
        if app.user_name is not None:
            self._require_ha1 = auth_ha1(app.user_name,
                                         app.password or "")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0",
                         config.get_int(f"{c}.port")
                         if port is None else port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._accept_thread: threading.Thread | None = None
        self.frames_served = 0
        self.cancelled_streams = 0

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="frame-accept")
        self._accept_thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: the accept thread is blocked in
            # accept(2) and a bare close leaves the listener fd alive
            # in the kernel (the port stays bound, a restart can't
            # rebind); shutdown wakes the accept with an error
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._workers.shutdown(wait=False)
        if self._accept_thread is not None:
            self._accept_thread.join(5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True, name="frame-conn").start()

    def _conn_loop(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wlock = threading.Lock()
        cancelled: set[int] = set()
        clock = threading.Lock()
        authed = self._require_ha1 is None
        try:
            while True:
                ftype, stream, payload = read_frame(rfile)
                if ftype == FRAME_AUTH:
                    try:
                        offered = json.loads(payload).get("ha1")
                    except (ValueError, AttributeError):
                        offered = None
                    if self._require_ha1 is not None \
                            and offered != self._require_ha1:
                        _log.warning("frame connection rejected: "
                                     "bad AUTH")
                        return
                    authed = True
                    continue
                if not authed:
                    _log.warning("frame connection rejected: first "
                                 "frame not AUTH")
                    return
                if ftype == FRAME_CANCEL:
                    with clock:
                        cancelled.add(stream)
                        if len(cancelled) > 4096:
                            # a CANCEL that crossed its RESP on the
                            # wire leaves an id nothing will ever
                            # consume; ids are per-connection
                            # monotonic, so on a long-lived connection
                            # those races would otherwise accumulate
                            # forever.  Clearing is benign: a false
                            # negative just writes a response the
                            # router demuxes to nothing.
                            cancelled.clear()
                            cancelled.add(stream)
                    self.cancelled_streams += 1
                    if self.metrics is not None:
                        self.metrics.inc("transport_cancelled_streams")
                    continue
                if ftype != FRAME_REQ:
                    continue  # unknown client frame: ignore
                try:
                    self._workers.submit(self._serve_frame, conn,
                                         wlock, cancelled, clock,
                                         stream, payload)
                except RuntimeError:
                    return  # pool shut down under us: server closing
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_frame(self, conn, wlock, cancelled, clock, stream,
                     payload) -> None:
        try:
            with clock:
                if stream in cancelled:
                    cancelled.discard(stream)
                    return  # cancelled before it ever started: no work
            header, body = _unpack_msg(payload)
            method = str(header.get("m", "GET"))
            path = str(header.get("p", "/"))
            headers = {str(k).title(): str(v)
                       for k, v in (header.get("h") or {}).items()}
            # chaos: ONE stream's answer stalls mid-frame — fired
            # per-stream BEFORE the write lock, so connection-mates
            # (and their hedges) keep flowing
            faults.fire("transport-frame-stall")
            status, rhdrs, out = self._answer(method, path, headers,
                                              body)
            with clock:
                if stream in cancelled:
                    cancelled.discard(stream)
                    return  # loser of a hedge: drop the bytes
            write_frame(conn, FRAME_RESP, stream,
                        _pack_msg({"s": status, "h": rhdrs}, out),
                        wlock)
            with clock:
                # a CANCEL racing the write above lands in the set
                # AFTER this stream already answered: reclaim it here
                # so the common race (timeout boundary) never leaks
                cancelled.discard(stream)
            self.frames_served += 1
        except (ConnectionError, OSError):
            pass  # connection died under the response: nothing to do
        except Exception:  # noqa: BLE001 — a dispatcher bug must not
            _log.exception("frame dispatch failed")  # kill the loop

    def _answer(self, method: str, path: str, headers: dict,
                body: bytes) -> tuple[int, dict, bytes]:
        cache = self.shard_cache
        base = path.split("?", 1)[0]
        cacheable = (cache is not None and cache.enabled
                     and base.startswith("/shard/")
                     and base != "/shard/meta")
        epoch0 = 0
        if cacheable:
            got = cache.lookup(method, path, body)
            if got is not None:
                return got
            epoch0 = cache.epoch()
        handler = _FrameHandler(method, path, headers, body)
        self.app.handle(handler)
        out = handler.wfile.getvalue()
        rhdrs = {k.lower(): v for k, v in handler.resp_headers.items()}
        if cacheable:
            cache.store(method, path, body, epoch0, handler.status,
                        rhdrs, out)
        return handler.status, rhdrs, out
