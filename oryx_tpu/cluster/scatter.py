"""Deadline-propagating, hedging, circuit-broken fan-out to shard
replicas.

One :class:`ScatterGather` lives on the router.  Per public request it
queries every catalog shard (``scatter``) or any one replica
(``any_replica`` — for endpoints answered from the replicated user
store).  Per shard it walks the membership registry's candidates
(ready, newest generation first) with *hedged* attempts: the first
replica gets ``hedge-after-ms`` to answer before a second attempt is
launched against the next replica — both stay in flight and the first
success wins, so one slow replica costs the hedge window, not the
whole deadline.  Every attempt runs behind a per-replica
:class:`~oryx_tpu.resilience.policy.CircuitBreaker` (a dead replica is
shed in microseconds until its half-open probe passes) and carries the
request's REMAINING deadline downstream as ``X-Deadline-Ms`` so a
shard never computes an answer nobody is waiting for.

Transport is a hand-rolled keep-alive HTTP/1.1 client over a per-URL
connection pool (the stdlib client's email-parser machinery costs real
qps at gateway rates — same reasoning as bench/load.py's driver).  It
speaks the replicas' whole front-door surface: TLS to ``https``
heartbeat URLs (unverified — the cluster-internal trust model for the
replicas' self-signed serving certs) and the serving tier's DIGEST
auth (``qop="auth"``; credentials from ``oryx.serving.api.user-name/
password``, so one shared ``--conf`` secures the public door and the
scatter plane alike), with one challenge round per replica URL and
cached-nonce reuse until the replica rotates its nonce set.

HTTP responses — ANY status — are authoritative: a 404 means "user
unknown", not "replica down", and must neither trip the breaker nor
trigger a hedge.  Only transport errors, timeouts, and 5xx count as
attempt failures.

Chaos seam: ``router-shard-timeout`` fires once per shard query
(mode=delay simulates a stalled shard eating the deadline; mode=error
a shard that fails outright — the partial-answer path's test handle).
"""

from __future__ import annotations

import hashlib
import json
import logging
import re
import secrets
import socket
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from queue import Empty, SimpleQueue
from typing import Sequence

from ..common import clock as clockmod
from ..api.serving import OryxServingException
from ..resilience import faults
from ..resilience.policy import CircuitBreaker, CircuitOpenError, Deadline
from .membership import Heartbeat, MembershipRegistry
from .transport import FrameTransport, StreamAbandoned

_log = logging.getLogger(__name__)

__all__ = ["ScatterGather", "ShardUnavailable", "ShardResponse"]


class ShardUnavailable(OryxServingException):
    """No replica of a shard produced an authoritative response within
    the deadline — the shard drops out of the merge (partial answer).
    An OryxServingException(503), so one escaping a router handler
    (every shard down, no replica for a vector gather) renders as the
    serving tier's standard 503 degrade, never a 500."""

    def __init__(self, message: str):
        super().__init__(503, message)


class ShardResponse:
    __slots__ = ("shard", "status", "payload", "replica")

    def __init__(self, shard: int, status: int, payload, replica: str):
        self.shard = shard
        self.status = status
        self.payload = payload
        self.replica = replica

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300


class _Pool:
    """Keep-alive socket pool per base URL.  ``https`` replica URLs get
    TLS without certificate verification: the scatter plane rides the
    cluster-internal network against the replicas' own (typically
    self-signed) serving certs, the same trust model the repo's TLS
    tests use client-side.

    Hygiene (``oryx.cluster.pool.*``): idle sockets age out after
    ``idle_ttl_sec`` and each URL's stack is bounded at
    ``max_per_url`` — with autoscaled replicas on ephemeral ports
    every spawn/retire cycle adds a URL, and an unbounded pool would
    pin dead sockets (and map entries) forever.  The sweep runs
    opportunistically on release, so an idle router still converges:
    its next request (or the periodic scrape) reclaims the lot."""

    def __init__(self, connect_timeout: float = 5.0,
                 idle_ttl_sec: float = 30.0, max_per_url: int = 64):
        # url -> [(socket, rfile, released_at_monotonic), ...]
        self._conns: dict[str, list[tuple]] = {}
        self._lock = threading.Lock()
        self.connect_timeout = connect_timeout
        self.idle_ttl_sec = idle_ttl_sec
        self.max_per_url = max(1, max_per_url)
        self._tls = None
        self._last_sweep = clockmod.monotonic()
        self.idle_evictions = 0
        self.cap_evictions = 0

    def acquire(self, url: str) -> tuple[tuple[socket.socket, object], bool]:
        """(connection, reused) — ``reused`` means keep-alive from the
        pool, which may have died since its last request.  Entries
        idle past the TTL are discarded on the way out: a socket that
        sat unused that long has likely been dropped by the far end
        (or a middlebox), and handing it out just buys a stale-socket
        retry."""
        now = clockmod.monotonic()
        stale = []
        try:
            with self._lock:
                stack = self._conns.get(url)
                while stack:
                    conn, rfile, released = stack.pop()
                    if now - released <= self.idle_ttl_sec:
                        return (conn, rfile), True
                    stale.append((conn, rfile))
                    self.idle_evictions += 1
        finally:
            for conn_rf in stale:
                self.discard(conn_rf)
        return self.fresh(url), False

    def fresh(self, url: str) -> tuple[socket.socket, object]:
        p = urllib.parse.urlparse(url)
        conn = socket.create_connection((p.hostname, p.port),
                                        timeout=self.connect_timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if p.scheme == "https":
            if self._tls is None:
                import ssl
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                self._tls = ctx
            conn = self._tls.wrap_socket(conn, server_hostname=p.hostname)
        return conn, conn.makefile("rb")

    def release(self, url: str, conn_rf) -> None:
        dropped = []
        with self._lock:
            stack = self._conns.setdefault(url, [])
            stack.append((conn_rf[0], conn_rf[1], clockmod.monotonic()))
            while len(stack) > self.max_per_url:
                # oldest-idle first: the bound sheds the sockets least
                # likely to be reused
                dropped.append(stack.pop(0))
                self.cap_evictions += 1
        for conn, rfile, _ in dropped:
            self.discard((conn, rfile))
        self._sweep()

    def _sweep(self) -> None:
        """Reclaim idle-past-TTL sockets across EVERY url and drop
        empty url keys — the long-gone-replica path: once its sockets
        age out nothing references the URL again."""
        now = clockmod.monotonic()
        stale = []
        with self._lock:
            if now - self._last_sweep < max(1.0, self.idle_ttl_sec / 4):
                return
            self._last_sweep = now
            for url in list(self._conns):
                stack = self._conns[url]
                keep = []
                for entry in stack:
                    if now - entry[2] <= self.idle_ttl_sec:
                        keep.append(entry)
                    else:
                        stale.append(entry)
                        self.idle_evictions += 1
                if keep:
                    self._conns[url] = keep
                else:
                    del self._conns[url]
        for conn, rfile, _ in stale:
            self.discard((conn, rfile))

    def pooled(self, url: str | None = None) -> int:
        """Pooled-socket count (per url, or total) — test/metrics
        introspection."""
        with self._lock:
            if url is not None:
                return len(self._conns.get(url, ()))
            return sum(len(s) for s in self._conns.values())

    def discard(self, conn_rf) -> None:
        # shutdown BEFORE close: a hedge-cancel closer runs on the
        # winner's thread while the loser is blocked in recv on this
        # socket — close() alone does not reliably wake a concurrent
        # reader; shutdown() does (the read returns EOF/ECONNRESET
        # and the loser exits through the abandoned path)
        try:
            conn_rf[0].shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn_rf[0].close()
        except OSError:
            pass

    def purge(self, url: str) -> None:
        """Drop every pooled connection for a URL — when one reused
        socket turns out dead (replica restart), its poolmates almost
        certainly are too."""
        with self._lock:
            stack = self._conns.pop(url, [])
        for conn, rfile, _ in stack:
            self.discard((conn, rfile))

    def close(self) -> None:
        with self._lock:
            for stack in self._conns.values():
                for conn, rfile, _ in stack:
                    self.discard((conn, rfile))
            self._conns.clear()


def _request(conn, rfile, method: str, path: str, body: bytes | None,
             headers: dict[str, str], timeout: float
             ) -> tuple[int, bytes, dict[str, str]]:
    conn.settimeout(max(0.001, timeout))
    head = [f"{method} {path} HTTP/1.1", "Host: oryx-cluster",
            "Accept: application/json"]
    head += [f"{k}: {v}" for k, v in headers.items()]
    if body is not None:
        head.append(f"Content-Length: {len(body)}")
        head.append("Content-Type: application/json")
    payload = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
    if body is not None:
        payload += body
    conn.sendall(payload)
    status_line = rfile.readline(65537)
    if not status_line:
        raise ConnectionError("replica closed connection")
    status = int(status_line.split(b" ", 2)[1])
    clen = 0
    rhdrs: dict[str, str] = {}
    while True:
        h = rfile.readline(65537)
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, value = h.partition(b":")
        rhdrs[name.strip().lower().decode("latin-1")] = \
            value.strip().decode("latin-1")
        if name.strip().lower() == b"content-length":
            clen = int(value)
    out = b""
    while len(out) < clen:
        got = rfile.read(clen - len(out))
        if not got:
            raise ConnectionError("short body from replica")
        out += got
    return status, out, rhdrs


class _CancelToken:
    """One hedged shard query's cancellation latch.  Each in-flight
    attempt registers a closer (close the HTTP socket / CANCEL the
    frame stream); when a sibling wins — or the query gives up — the
    token fires every registered closer, so the losers are torn down
    NOW instead of finishing reads nobody will consume and returning
    possibly-stalled sockets to the keep-alive pool."""

    __slots__ = ("_lock", "_closers", "_next", "fired")

    def __init__(self):
        self._lock = threading.Lock()
        self._closers: dict[int, object] = {}
        self._next = 0
        self.fired = False

    def register(self, closer) -> int | None:
        """None when the token already fired (the race is over before
        this attempt got started)."""
        with self._lock:
            if self.fired:
                return None
            self._next += 1
            self._closers[self._next] = closer
            return self._next

    def update(self, key: int, closer) -> bool:
        with self._lock:
            if self.fired:
                return False
            self._closers[key] = closer
            return True

    def unregister(self, key: int) -> None:
        with self._lock:
            self._closers.pop(key, None)

    def fire(self) -> None:
        with self._lock:
            if self.fired:
                return
            self.fired = True
            closers = list(self._closers.values())
            self._closers.clear()
        for fn in closers:
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass


# sentinel threaded through the breaker for a cancelled loser: a
# normal return, so the breaker never counts failure evidence against
# a replica that was merely slower than its hedge sibling
_ABANDONED = object()


class _DigestAuth:
    """DIGEST client for the replicas' challenge (the serving tier's
    MD5 ``qop="auth"`` scheme — lambda_rt/http.py `_auth_ok`).  One
    challenge round per replica URL, then the cached nonce is reused
    with an incrementing nc; when the replica rotates its nonce set
    (401 on a previously good nonce) the caller re-challenges."""

    def __init__(self, user: str, password: str):
        self.user = user
        self.password = password or ""
        # url -> (realm, nonce, next nc)
        self._state: dict[str, tuple[str, str, int]] = {}
        self._lock = threading.Lock()

    def challenge(self, url: str, www_authenticate: str) -> bool:
        pairs = re.findall(r'(\w+)=(?:"([^"]*)"|([^, ]*))',
                           www_authenticate)
        parts = {k: (q or b) for k, q, b in pairs}
        if "nonce" not in parts:
            return False
        with self._lock:
            self._state[url] = (parts.get("realm", ""), parts["nonce"], 1)
        return True

    def header(self, url: str, method: str, uri: str) -> str | None:
        with self._lock:
            st = self._state.get(url)
            if st is None:
                return None
            realm, nonce, nc = st
            self._state[url] = (realm, nonce, nc + 1)
        cnonce = secrets.token_hex(8)
        ncs = f"{nc:08x}"

        def md5(s: str) -> str:
            return hashlib.md5(s.encode()).hexdigest()

        ha1 = md5(f"{self.user}:{realm}:{self.password}")
        ha2 = md5(f"{method}:{uri}")
        response = md5(f"{ha1}:{nonce}:{ncs}:{cnonce}:auth:{ha2}")
        return (f'Digest username="{self.user}", realm="{realm}", '
                f'nonce="{nonce}", uri="{uri}", qop=auth, nc={ncs}, '
                f'cnonce="{cnonce}", response="{response}"')


class ScatterGather:
    def __init__(self, registry: MembershipRegistry, config,
                 max_concurrency: int = 64, tracer=None):
        self.registry = registry
        # obs/trace.py tracer (None = tracing off): each shard query of
        # a sampled request gets a `router.shard_call` span whose
        # context rides the internal hop as the `traceparent` header,
        # so the replica's own request span parents under it
        self.tracer = tracer
        # unsampled requests must ALSO propagate context (flags 00):
        # sampling is decided once at the root, and without the header
        # a tracing-enabled replica would re-roll its own dice on every
        # internal hop.  One process-constant string keeps the
        # unsampled hot path allocation-free.
        self._unsampled_tp = None
        if tracer is not None:
            from ..obs.trace import unsampled_traceparent
            self._unsampled_tp = unsampled_traceparent()
        c = "oryx.cluster"
        self.hedge_after_sec = config.get_int(f"{c}.hedge-after-ms") / 1000.0
        self.shard_timeout_sec = \
            config.get_int(f"{c}.shard-timeout-ms") / 1000.0
        self.max_attempts = config.get_int(f"{c}.max-attempts-per-shard")
        self._config = config
        self._pool = _Pool(
            idle_ttl_sec=config.get_int(
                f"{c}.pool.idle-ttl-ms") / 1000.0,
            max_per_url=config.get_int(f"{c}.pool.max-per-url"))
        # multiplexed framed transport (cluster/transport.py): when
        # enabled, attempts against replicas that advertise a
        # transport port ride one persistent framed connection per
        # replica; the HTTP/1.1 pool stays the fallback for replicas
        # that don't (mixed-fleet rollout)
        self.transport = FrameTransport(config) \
            if config.get_bool(f"{c}.transport.enabled") else None
        user = config.get_optional_string("oryx.serving.api.user-name")
        self._auth = _DigestAuth(
            user, config.get_optional_string("oryx.serving.api.password")
        ) if user else None
        self._exec = ThreadPoolExecutor(max_workers=max_concurrency,
                                        thread_name_prefix="router-scatter")
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        # operator counters (router /metrics)
        self.hedges = 0
        self.shard_failures = 0
        self.partial_answers = 0
        self.group_failovers = 0
        # hedged losers torn down mid-flight instead of finishing
        # reads nobody consumes (and poisoning the keep-alive pool)
        self.hedge_abandoned = 0
        # replica url -> (reported scoring queue-wait ms, seen
        # monotonic): piggybacked on every shard envelope, the live
        # overload signal the router's admission control reads
        self._queue_waits: dict[str, tuple[float, float]] = {}
        self._qw_cache: tuple[float | None, float] = (None, -1e9)

    # how long a replica's reported queue wait stays a valid admission
    # signal; past this (replica silent / not queried) it is ignored
    QUEUE_WAIT_TTL_SEC = 10.0
    # the aggregated signal is an envelope-rate EWMA — recomputing the
    # shards x group walk (registry lock + rotation) on EVERY admitted
    # request buys nothing; a short-lived cache keeps the admission
    # gate near-zero cost on the hot path
    QUEUE_WAIT_CACHE_SEC = 0.25

    def note_queue_wait(self, url: str, ms: float) -> None:
        with self._lock:
            self._queue_waits[url] = (ms, clockmod.monotonic())

    def cluster_queue_wait_ms(self) -> float | None:
        """The cluster's effective scoring queue wait: per shard the
        MIN over its replica group (the best member routing could
        pick), then the MAX over shards (every scatter waits for its
        slowest shard).  None until any replica has reported."""
        now = clockmod.monotonic()
        with self._lock:
            value, at = self._qw_cache
            if now - at <= self.QUEUE_WAIT_CACHE_SEC:
                return value
            # evict long-dead entries: with autoscaled members on
            # ephemeral ports every spawn/retire cycle adds a URL, and
            # TTL-ignoring without removal would grow the map forever
            dead = [u for u, (_, seen) in self._queue_waits.items()
                    if now - seen > 6 * self.QUEUE_WAIT_TTL_SEC]
            for u in dead:
                del self._queue_waits[u]
            waits = dict(self._queue_waits)
        worst, seen = 0.0, False
        for shard in range(self.registry.shard_count):
            best = None
            for hb in self.registry.candidates(shard):
                v = waits.get(hb.url)
                if v is not None and now - v[1] <= self.QUEUE_WAIT_TTL_SEC:
                    best = v[0] if best is None else min(best, v[0])
            if best is not None:
                seen = True
                worst = max(worst, best)
        out = worst if seen else None
        with self._lock:
            self._qw_cache = (out, now)
        return out

    def close(self) -> None:
        self._exec.shutdown(wait=False)
        self._pool.close()
        if self.transport is not None:
            self.transport.close()

    def _breaker(self, url: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(url)
            if b is None:
                b = CircuitBreaker.from_config(
                    f"router-replica[{url}]", self._config)
                self._breakers[url] = b
            return b

    # -- one attempt ---------------------------------------------------------

    def _attempt(self, hb: Heartbeat, shard: int, method: str, path: str,
                 body: bytes | None, deadline: Deadline | None,
                 traceparent: str | None = None, cancel=None):
        timeout = self.shard_timeout_sec
        headers = {}
        if traceparent:
            headers["Traceparent"] = traceparent
        if deadline is not None:
            remaining = deadline.remaining()
            if remaining <= 0.0:
                raise ShardUnavailable("deadline exhausted")
            timeout = min(timeout, remaining)
            # remaining-budget propagation: the shard sheds work the
            # router would no longer wait for
            headers["X-Deadline-Ms"] = str(max(1, int(remaining * 1000)))

        if self.transport is not None and getattr(hb, "tport", None):
            # the multiplexed framed hop: one persistent connection
            # per replica, this attempt is one more interleaved stream
            # on it (auth is the connection-level AUTH frame)
            out = self._breaker(hb.url).call(
                self._framed_call, hb, shard, method, path, body,
                headers, timeout, traceparent, cancel)
            if out is _ABANDONED:
                raise StreamAbandoned(f"hedge abandoned for {hb.url}")
            return out

        if self._auth is not None:
            h = self._auth.header(hb.url, method, path)
            if h:
                headers["Authorization"] = h

        # the closer a firing cancel token runs: close THE CURRENT
        # in-flight socket so the loser's blocked read dies now —
        # holder[0] tracks it across the stale-socket retry, and is
        # cleared before release so a pooled socket is never closed
        holder = [None]

        def close_inflight():
            conn_rf = holder[0]
            if conn_rf is not None:
                self._pool.discard(conn_rf)

        def call():
            conn_rf, reused = self._pool.acquire(hb.url)
            holder[0] = conn_rf
            ckey = None
            if cancel is not None:
                ckey = cancel.register(close_inflight)
                if ckey is None:
                    # the race was over before this attempt started
                    holder[0] = None
                    self._pool.release(hb.url, conn_rf)
                    return self._abandon()
            try:
                try:
                    status, raw, rhdrs = _request(conn_rf[0], conn_rf[1],
                                                  method, path, body,
                                                  headers, timeout)
                except ConnectionError:
                    if cancel is not None and cancel.fired:
                        self._pool.discard(conn_rf)
                        return self._abandon()
                    # a reused keep-alive socket died between requests
                    # (the replica restarted — a designed, supervised
                    # event): that is a property of THIS socket, not of
                    # the replica, so retry once on a fresh connection
                    # before letting the failure count against the
                    # breaker.  Internal queries are all idempotent
                    # reads.  Timeouts deliberately do NOT retry (a
                    # slow replica must cost one window, not two).
                    self._pool.discard(conn_rf)
                    if not reused:
                        raise
                    self._pool.purge(hb.url)
                    conn_rf = self._pool.fresh(hb.url)
                    holder[0] = conn_rf
                    if cancel is not None and cancel.fired:
                        self._pool.discard(conn_rf)
                        return self._abandon()
                    try:
                        status, raw, rhdrs = _request(conn_rf[0],
                                                      conn_rf[1],
                                                      method, path, body,
                                                      headers, timeout)
                    except BaseException:
                        self._pool.discard(conn_rf)
                        if cancel is not None and cancel.fired:
                            return self._abandon()
                        raise
                except BaseException:
                    self._pool.discard(conn_rf)
                    if cancel is not None and cancel.fired:
                        return self._abandon()
                    raise
                if status == 401 and self._auth is not None and \
                        self._auth.challenge(
                            hb.url, rhdrs.get("www-authenticate", "")):
                    # first contact, or the replica rotated its nonce
                    # set: answer the fresh challenge once on the same
                    # keep-alive connection (the 401 carries
                    # Content-Length: 0)
                    headers["Authorization"] = self._auth.header(
                        hb.url, method, path)
                    try:
                        status, raw, rhdrs = _request(conn_rf[0],
                                                      conn_rf[1],
                                                      method, path, body,
                                                      headers, timeout)
                    except BaseException:
                        self._pool.discard(conn_rf)
                        if cancel is not None and cancel.fired:
                            return self._abandon()
                        raise
            finally:
                if ckey is not None:
                    cancel.unregister(ckey)
            holder[0] = None
            if cancel is not None and cancel.fired:
                # won race landed between the read and here: the
                # socket's state is unknowable (the closer may have
                # fired mid-release) — never pool it
                self._pool.discard(conn_rf)
            else:
                self._pool.release(hb.url, conn_rf)
            return self._finish_attempt(hb, shard, status, raw)

        out = self._breaker(hb.url).call(call)
        if out is _ABANDONED:
            raise StreamAbandoned(f"hedge abandoned for {hb.url}")
        return out

    def _abandon(self):
        with self._lock:
            self.hedge_abandoned += 1
        return _ABANDONED

    def _framed_call(self, hb, shard, method, path, body, headers,
                     timeout, traceparent, cancel):
        t0 = clockmod.monotonic()
        try:
            status, raw, _ = self.transport.request(
                hb, method, path, body, headers, timeout, cancel=cancel)
        except StreamAbandoned:
            return self._abandon()
        self._record_frame_span(traceparent, t0, clockmod.monotonic(),
                                hb, shard, status)
        return self._finish_attempt(hb, shard, status, raw)

    def _record_frame_span(self, tp, t0, t1, hb, shard, status) -> None:
        """Retroactive ``transport.frame_call`` span under the sampled
        request's shard_call — the framed hop's wire time, named so a
        slow frame is attributable separately from replica compute."""
        if self.tracer is None or not tp:
            return
        from ..obs.trace import parse_traceparent
        ctx = parse_traceparent(tp)
        if not ctx or not ctx[2]:
            return
        self.tracer.record_span(
            "transport.frame_call", (ctx[0], ctx[1]), t0, t1,
            attrs={"replica": hb.url, "shard": shard,
                   "http.status": status})

    def _finish_attempt(self, hb, shard: int, status: int,
                        raw: bytes) -> ShardResponse:
        """Shared attempt epilogue for both transports: parse the JSON
        envelope, harvest the queue-wait piggyback, and fail over on
        5xx exactly like a transport fault."""
        payload = None
        if raw:
            try:
                payload = json.loads(raw)
            except ValueError:
                payload = {"error": raw[:512].decode("latin-1")}
        if isinstance(payload, dict) \
                and "queue_wait_ms" in payload:
            try:
                self.note_queue_wait(hb.url,
                                     float(payload["queue_wait_ms"]))
            except (TypeError, ValueError):
                pass  # malformed envelope field: not load-bearing
        if status >= 500:
            # replica answered but is unhealthy (lost its model,
            # internal error): failover like a transport fault
            raise ConnectionError(f"replica {hb.url} -> {status}")
        return ShardResponse(shard, status, payload, hb.url)

    # -- hedged per-shard query ---------------------------------------------

    def query_shard(self, shard: int, method: str, path: str,
                    body: bytes | None = None,
                    deadline: Deadline | None = None,
                    parent_span=None,
                    candidates: "list[Heartbeat] | None" = None
                    ) -> ShardResponse:
        """Authoritative response from ``shard``, via hedged attempts
        over its live replicas; :class:`ShardUnavailable` when none
        answers within the deadline.

        ``parent_span`` is the caller's request span when this call
        runs on a pool thread (scatter fan-out) where thread-local
        trace context does not follow; called inline on the handler
        thread, the tracer's thread-current span is used.
        ``candidates`` is the scatter fan-out's consistent routing-plan
        slice (registry.routing_plan()); None re-reads the registry —
        fine for single-shard callers like the Gramian fetch."""
        faults.fire("router-shard-timeout")
        span, tp = self._begin_shard_span(shard, parent_span)
        try:
            res = self._query_shard(shard, method, path, body, deadline,
                                    tp, candidates=candidates)
        except BaseException:
            if span is not None:
                span.end("error")
            raise
        if span is not None:
            span.set_attr("replica", res.replica)
            span.set_attr("http.status", res.status)
            span.end()
        return res

    def _begin_shard_span(self, shard: int, parent_span):
        """(span, traceparent) for one shard query — (None, None) when
        tracing is off, (None, flags-00 context) when the root decided
        not to sample."""
        if self.tracer is None:
            return None, None
        parent = parent_span if parent_span is not None \
            else self.tracer.current()
        span = self.tracer.child_span(parent, "router.shard_call")
        if not span.sampled:
            return None, self._unsampled_tp
        span.set_attr("shard", shard)
        return span, span.traceparent()

    def _query_shard(self, shard: int, method: str, path: str,
                     body: bytes | None, deadline: Deadline | None,
                     tp: str | None,
                     candidates: "list[Heartbeat] | None" = None
                     ) -> ShardResponse:
        if candidates is None:
            candidates = self.registry.candidates(shard)
        if not candidates:
            with self._lock:
                self.shard_failures += 1
            raise ShardUnavailable(f"shard {shard}: no live ready replica")
        if len(candidates) == 1:
            # nothing to hedge against: run the single attempt inline
            # (per-request thread spawns are measurable at gateway qps)
            try:
                return self._attempt(candidates[0], shard, method, path,
                                     body, deadline, tp)
            except ShardUnavailable:
                with self._lock:
                    self.shard_failures += 1
                raise
            except Exception as e:  # noqa: BLE001 — one shot only
                with self._lock:
                    self.shard_failures += 1
                raise ShardUnavailable(
                    f"shard {shard}: {type(e).__name__}: {e}") from e
        box: SimpleQueue = SimpleQueue()
        errors: list[BaseException] = []
        in_flight = 0
        # hedge cancellation: the moment one attempt wins (or the
        # query gives up), every other in-flight attempt is torn down
        # — a socket close on the HTTP hop, a CANCEL frame on the
        # framed hop — so a stalled replica can't poison the
        # keep-alive pool with a mid-response socket and never
        # computes an answer nobody is waiting for
        cancel = _CancelToken()

        def attempt_async(hb: Heartbeat) -> None:
            def run():
                try:
                    box.put(self._attempt(hb, shard, method, path, body,
                                          deadline, tp, cancel=cancel))
                except BaseException as e:  # noqa: BLE001 — collected
                    box.put(e)
            threading.Thread(target=run, daemon=True,
                             name=f"router-hedge-s{shard}").start()

        def drain(window: float | None) -> ShardResponse | None:
            """Wait up to ``window`` (None = until deadline/timeout) for
            a success; failures decrement in-flight and keep waiting."""
            nonlocal in_flight
            t_end = clockmod.monotonic() + (window if window is not None
                                        else self.shard_timeout_sec)
            if deadline is not None:
                t_end = min(t_end, deadline.t_end)
            while in_flight:
                wait = t_end - clockmod.monotonic()
                if wait <= 0:
                    return None
                try:
                    got = box.get(timeout=wait)
                except Empty:
                    return None
                in_flight -= 1
                if isinstance(got, ShardResponse):
                    return got
                errors.append(got)
                if isinstance(got, ShardUnavailable):
                    # deadline exhausted inside the attempt: no point
                    # waiting for more
                    return None
            return None

        try:
            for i, hb in enumerate(candidates[:self.max_attempts]):
                if deadline is not None and deadline.expired:
                    break
                attempt_async(hb)
                in_flight += 1
                last = (i + 1 >= min(len(candidates), self.max_attempts))
                res = drain(None if last else self.hedge_after_sec)
                if res is not None:
                    if errors:
                        # a sibling answered after a group member
                        # FAILED (not merely hedged): the replica-group
                        # failover evidence — a dead member costs
                        # latency, never coverage
                        with self._lock:
                            self.group_failovers += 1
                    return res
                if not last:
                    with self._lock:
                        self.hedges += 1
            res = drain(None)
            if res is not None:
                if errors:
                    with self._lock:
                        self.group_failovers += 1
                return res
        finally:
            # win or give-up: the losers are cancelled NOW (counted
            # in hedge_abandoned), never left to finish reads nobody
            # consumes
            cancel.fire()
        with self._lock:
            self.shard_failures += 1
        detail = "; ".join(f"{type(e).__name__}: {e}" for e in errors[-3:])
        raise ShardUnavailable(
            f"shard {shard}: no replica answered ({detail or 'timeout'})")

    # -- fan-out -------------------------------------------------------------

    def scatter(self, method: str, paths: "dict[int, str] | str",
                body: bytes | None = None,
                deadline: Deadline | None = None,
                shards: "Sequence[int] | None" = None
                ) -> tuple[dict[int, ShardResponse], list[int]]:
        """Query every shard — or only ``shards`` when given (e.g. the
        Gramian cache fetching just the shards whose generation moved).
        ``paths`` is one path for all shards or a per-shard map.
        Returns (responses by shard, failed shards).  Raises
        ShardUnavailable only when EVERY queried shard failed."""
        # ONE consistent routing snapshot for the whole fan-out: the
        # topology and every shard's candidate list come from a single
        # locked registry read, so a cutover mid-request can never mix
        # two rings' shards into one merge (the atomic-cutover
        # contract; a request in flight at the cutover instant routes
        # entirely on the ring it started with)
        of, plan = self.registry.routing_plan()
        if shards is None:
            targets = range(of)
            plan_for = {s: plan[s] for s in targets}
        else:
            targets = shards
            # explicit-shard callers (the Gramian cache) key their own
            # state by (topology, shard, generation); candidates
            # re-read per shard as before
            plan_for = {s: None for s in targets}
        # trace context is captured HERE, on the requesting handler
        # thread — the per-shard queries run on pool threads where the
        # tracer's thread-local current span does not follow
        parent = self.tracer.current() if self.tracer is not None \
            else None
        futures = {
            s: self._exec.submit(
                self.query_shard, s,
                method, paths if isinstance(paths, str) else paths[s],
                body, deadline, parent, plan_for[s])
            for s in targets}
        results: dict[int, ShardResponse] = {}
        failed: list[int] = []
        # collection bound: the REQUEST deadline (plus a small grace for
        # result plumbing), not the per-attempt transport cap — a shard
        # stalled mid-attempt must degrade to a partial answer by the
        # deadline, not hold the whole response for the transport cap
        for s, f in futures.items():
            try:
                results[s] = f.result(
                    timeout=self.shard_timeout_sec + 1.0
                    if deadline is None
                    else max(0.05, deadline.remaining()) + 0.25)
            except Exception as e:  # noqa: BLE001 — shard drops out
                _log.warning("shard %d dropped from merge: %s", s, e)
                failed.append(s)
        if not results:
            raise ShardUnavailable(
                f"all {len(futures)} queried shard(s) unavailable")
        if failed:
            with self._lock:
                self.partial_answers += 1
        return results, failed

    def any_replica(self, method: str, path: str,
                    body: bytes | None = None,
                    deadline: Deadline | None = None) -> ShardResponse:
        """Authoritative response from any ready replica (endpoints
        answered from the replicated user store)."""
        candidates = self.registry.any_candidates()
        if not candidates:
            raise ShardUnavailable("no live ready replica")
        span, tp = self._begin_shard_span(-1, None)
        last: BaseException | None = None
        for hb in candidates[:max(self.max_attempts, 1)]:
            try:
                res = self._attempt(hb, hb.shard, method, path, body,
                                    deadline, tp)
            except (ShardUnavailable, CircuitOpenError,
                    OSError, ConnectionError, ValueError) as e:
                last = e
                continue
            if span is not None:
                span.set_attr("shard", hb.shard)
                span.set_attr("replica", res.replica)
                span.set_attr("http.status", res.status)
                span.end()
            return res
        if span is not None:
            span.end("error")
        raise ShardUnavailable(f"no replica answered: {last}")

    def scrape_replicas(self, path: str,
                        deadline: Deadline | None = None,
                        method: str = "GET"
                        ) -> list[tuple[Heartbeat, dict]]:
        """Best-effort request against EVERY live ready replica — not
        one per shard like ``scatter`` — returning ``(heartbeat,
        payload)`` for each 2xx JSON answer.  The cluster-wide metrics
        merge needs every replica's histogram buckets; a replica that
        fails or stalls is simply absent from the merge (the
        exposition reports how many were scraped).  ``method="POST"``
        drives the cluster-wide control fan-outs (the flight
        recorder's correlated dump) over the same transport."""
        candidates = self.registry.any_candidates()
        if not candidates:
            return []
        # scrapes are control plane, never trace roots: mark them
        # explicitly unsampled so replicas don't sample 1% of them
        futures = [(hb, self._exec.submit(self._attempt, hb, hb.shard,
                                          method, path, None, deadline,
                                          self._unsampled_tp))
                   for hb in candidates]
        out: list[tuple[Heartbeat, dict]] = []
        for hb, f in futures:
            try:
                r = f.result(timeout=self.shard_timeout_sec + 1.0
                             if deadline is None
                             else max(0.05, deadline.remaining()) + 0.25)
            except Exception:  # noqa: BLE001 — replica drops from merge
                continue
            if r.ok and isinstance(r.payload, dict):
                out.append((hb, r.payload))
        return out

    def stats(self) -> dict:
        qw = self.cluster_queue_wait_ms()
        with self._lock:
            out = {"hedges": self.hedges,
                   "shard_failures": self.shard_failures,
                   "partial_answers": self.partial_answers,
                   "group_failovers": self.group_failovers,
                   "hedge_abandoned": self.hedge_abandoned,
                   "cluster_queue_wait_ms":
                       None if qw is None else round(qw, 2),
                   "pool": {"sockets": self._pool.pooled(),
                            "idle_evictions": self._pool.idle_evictions,
                            "cap_evictions": self._pool.cap_evictions}}
        if self.transport is not None:
            out["transport"] = {
                "open_connections": self.transport.open_connections(),
                "per_replica": self.transport.connection_snapshot(),
                "cancels_sent": self.transport.cancels_sent,
                "reconnects": self.transport.reconnects,
            }
        return out
