"""Measured-queue-wait admission control for the scatter-gather router.

The r06 grid's unsustained rungs showed the failure shape of an
un-gated front end: past the device roofline, queues grow without
bound, every request's latency inherits the whole backlog, and
throughput COLLAPSES below what the hardware could sustain — the
classic open-loop overload spiral.  The honest degrade is to refuse
work the cluster demonstrably cannot finish: a fast ``503`` with a
``Retry-After`` header costs microseconds, keeps the admitted
requests' latency bounded, and gives well-behaved clients an explicit
backoff signal.

Two measured gates, both off by default (``oryx.cluster.admission.*``):

- **max-inflight** — a hard cap on concurrently executing data-plane
  requests at the router.  The scatter path blocks a handler thread
  per request, so in-flight count IS the router's queue depth.
- **queue-wait-high-ms** — the cluster's *measured* scoring queue wait
  (every shard envelope piggybacks the replica batcher's
  enqueue→dispatch EWMA; the scatter keeps the freshest value per
  replica, and the cluster signal is max over shards of min over each
  shard's replica group).  When even the best routing choice would
  queue longer than the threshold, new work is shed at the door.

Only routes marked ``admission=True`` (the scan/scatter data plane)
are gated; ``/ready``, ``/metrics`` and the admin surface stay open so
operators can see INTO an overloaded router.  Rejections count as
``admission_rejects`` on the router's metrics.
"""

from __future__ import annotations

import threading

__all__ = ["AdmissionController"]


class AdmissionController:
    """try_acquire()/release() around a request; constructed from
    ``oryx.cluster.admission.*`` (both gates 0 = disabled)."""

    def __init__(self, config, scatter, metrics=None):
        c = "oryx.cluster.admission"
        self.max_inflight = config.get_int(f"{c}.max-inflight")
        self.queue_wait_high_ms = config.get_int(
            f"{c}.queue-wait-high-ms")
        self.retry_after_sec = max(1, config.get_int(
            f"{c}.retry-after-sec"))
        self._scatter = scatter
        self._metrics = metrics
        self._lock = threading.Lock()
        self.inflight = 0
        self.rejected = 0

    @property
    def enabled(self) -> bool:
        return self.max_inflight > 0 or self.queue_wait_high_ms > 0

    def try_acquire(self) -> tuple[bool, int]:
        """(admitted, retry-after seconds).  Admitted callers MUST
        release()."""
        with self._lock:
            if self.max_inflight > 0 \
                    and self.inflight >= self.max_inflight:
                return self._reject_locked()
            self.inflight += 1
        if self.queue_wait_high_ms > 0:
            qw = self._scatter.cluster_queue_wait_ms()
            if qw is not None and qw > self.queue_wait_high_ms:
                with self._lock:
                    self.inflight -= 1
                    return self._reject_locked()
        return True, 0

    def _reject_locked(self) -> tuple[bool, int]:
        self.rejected += 1
        if self._metrics is not None:
            # inc takes its own lock; safe under ours (no inverse order)
            self._metrics.inc("admission_rejects")
        return False, self.retry_after_sec

    def release(self) -> None:
        with self._lock:
            self.inflight -= 1

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "inflight": self.inflight,
                    "rejected": self.rejected,
                    "max_inflight": self.max_inflight,
                    "queue_wait_high_ms": self.queue_wait_high_ms}
