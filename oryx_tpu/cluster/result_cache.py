"""Router hot path: exact result cache, fold-in invalidation, and
single-flight coalescing.

Every routed request today pays scatter → shard-score → gather →
exact-merge → JSON-encode.  BENCH_GATEWAY rounds put that path — not
the device — at the throughput ceiling, while the workload's structure
says most of the work is redundant: recommendation traffic is heavily
skewed toward hot users and identical repeated queries, and the model
only changes at generation publishes and per-user UP fold-ins.  This
module exploits exactly that structure:

**Exact result cache.**  Key = (route class, canonicalized path+args,
model generation, topology id); value = the *fully rendered* response
body — JSON bytes rendered at store time, CSV and gzip variants
rendered once on first demand — in a bounded LRU with a byte budget.
Only complete, header-less 200s are cacheable: partial answers
(``X-Oryx-Partial``), errors, bodiless (None) results, and requests
carrying ``rescorerParams``
(a per-request rescorer parameterization the router cannot prove pure)
are never stored.  A hit bypasses ``json_or_csv``, gzip, and admission
shedding entirely (it costs no device or queue time), stamped
``X-Oryx-Cache: hit``.

**Precise invalidation — no TTLs.**  The router already tails the
update topic for HB membership; the same tap feeds the cache:

- an UP record names the user (``["X", user, vec, ...]``) or item
  (``["Y", item, vec, [user]]``) the speed layer's fold-in touched —
  exactly that user's / item's tagged keys are evicted, nobody else's;
- a MODEL/MODEL-REF publish or a topology cutover flushes the epoch
  wholesale (the generation and topology also live in the key, so a
  stale epoch could never be *served* — the flush reclaims the bytes
  and is the safety valve when the invalidation feed stalls, chaos
  point ``router-cache-stale-feed``).

Entries additionally refuse to store (or to be shared with coalesced
followers) when any of their tags was invalidated after the request
began or within the quarantine window just before it (``_seq``
fencing + ``invalidation-quarantine-ms``): a scatter that read
pre-fold-in replica state can never insert over a newer invalidation.
Freshness contract, per tag: once the tap has a user's/item's UP
record, that user's/item's keys never serve their pre-fold-in rows
again (bounded by the tap's replay lag — the tap and the replicas
consume the same totally ordered topic).  Cross-entry effects — an
untouched user's cached ranking over item vectors some OTHER user's
fold-in nudged — persist until that entry's own tags are touched, it
is evicted, or the next generation publish: the same freshness the
speed layer itself gives untouched users (the residual-window
argument in docs/SCALING.md).

**Negative caching (hot 404s).**  An "unknown user/item" answer on the
cacheable surface is cached as a NEGATIVE entry under the same
generation/topology epoch (``oryx.cluster.cache.negative-enabled``):
a hot missing id stops costing a full scatter per probe.  Eviction is
the same precise UP feed — the fold-in that finally *creates* the
user/item names it in an UP record, which evicts its 404 — and the
``X-Oryx-Cache`` verdict semantics are unchanged (a cached 404 serves
as ``hit``, re-rendered through the same error page as a cold one).

**Single-flight coalescing.**  Concurrent requests with the same cache
key latch onto one in-flight scatter: the first becomes the *leader*,
followers wait on its flight and reuse the complete rendered result
(``X-Oryx-Cache: coalesced``).  A leader that dies (chaos point
``router-coalesce-leader-death``) wakes its followers empty-handed and
they fall through to their own scatter — coalescing can save work,
never lose a request.

Config: ``oryx.cluster.cache.*`` / ``oryx.cluster.coalesce.*`` (both
off by default).  Observable: ``cache_hits`` / ``cache_misses`` /
``cache_evictions`` / ``cache_invalidations`` /
``coalesced_requests`` / ``cache_stale_feed_stalls`` counters, the
``router.cache_lookup`` span, and the ``/admin/cache`` stats + flush
endpoint (docs/OBSERVABILITY.md, docs/SCALING.md).
"""

from __future__ import annotations

import gzip as gzip_mod
import json
import threading
from collections import OrderedDict
from typing import Callable, NamedTuple

from ..common import clock as clockmod
from ..resilience import faults

_monotonic = clockmod.monotonic

__all__ = ["ResultCache", "CacheEntry", "CacheProbe", "route_tags",
           "ShardResultCache"]


def _ids_of_segments(raw: str) -> tuple[str, ...]:
    """Item ids from an ``i1=2.5/i2/i3=0.5`` path tail (the id part of
    parse_id_value_segments, without importing the serving app)."""
    out = []
    for seg in raw.split("/"):
        if seg:
            out.append(seg.rsplit("=", 1)[0] if "=" in seg else seg)
    return tuple(out)


# route pattern -> (user ids, item ids) the response depends on through
# the speed layer's per-user/per-item fold-ins.  Only patterns listed
# here are cacheable; global aggregates (mostPopularItems, allItemIDs,
# ...) change on ANY ingest and have no precise invalidation key.
_ROUTE_TAGS: dict[str, Callable[[dict], tuple[tuple[str, ...],
                                              tuple[str, ...]]]] = {
    "/recommend/{userID}":
        lambda p: ((p["userID"],), ()),
    "/recommendToMany/{userIDs:+}":
        lambda p: (tuple(p["userIDs"].split("/")), ()),
    "/recommendToAnonymous/{itemIDs:+}":
        lambda p: ((), _ids_of_segments(p["itemIDs"])),
    "/recommendWithContext/{userID}/{itemIDs:+}":
        lambda p: ((p["userID"],), _ids_of_segments(p["itemIDs"])),
    "/similarity/{itemIDs:+}":
        lambda p: ((), tuple(p["itemIDs"].split("/"))),
    "/similarityToItem/{toItemID}/{itemIDs:+}":
        lambda p: ((), (p["toItemID"],) + tuple(p["itemIDs"].split("/"))),
    "/estimate/{userID}/{itemIDs:+}":
        lambda p: ((p["userID"],), tuple(p["itemIDs"].split("/"))),
    "/estimateForAnonymous/{toItemID}/{itemIDs:+}":
        lambda p: ((), (p["toItemID"],) + _ids_of_segments(p["itemIDs"])),
    "/because/{userID}/{itemID}":
        lambda p: ((p["userID"],), (p["itemID"],)),
    "/mostSurprising/{userID}":
        lambda p: ((p["userID"],), ()),
    "/knownItems/{userID}":
        lambda p: ((p["userID"],), ()),
}


def route_tags(pattern: str, params: dict
               ) -> tuple[tuple, tuple] | None:
    """(user tags, item tags) for a cacheable route pattern, or None
    when the pattern has no precise invalidation key."""
    fn = _ROUTE_TAGS.get(pattern)
    return fn(params) if fn is not None else None


class CacheProbe(NamedTuple):
    """One request's cache coordinates: minted before the lookup,
    carried to the store so insertion can be fenced against
    invalidations that ran while the scatter was in flight."""

    key: tuple
    tags: tuple          # (("u", id) | ("i", id), ...)
    epoch: tuple         # (topology, per-shard generations, mixed)
    seq: int             # invalidation sequence at probe time
    t: float             # cache clock at probe time (quarantine fence)


class CacheEntry:
    """A complete 200 answer, stored as its Python value plus rendered
    wire variants.  The JSON body is rendered at store time (the common
    case — it doubles as the leader's own response, so a hit is
    byte-identical to the miss that created it); the CSV and gzip
    variants render once on first demand and are charged to the byte
    budget as they appear.

    A NEGATIVE entry (``status`` != 200 — the hot-404 cache) retains
    only the error message: the dispatcher re-renders the error page
    from it per request (byte-identical to a cold 404 by construction,
    Accept negotiation included), so what the cache saves is the
    scatter, and the ``X-Oryx-Cache`` verdict semantics are
    unchanged."""

    __slots__ = ("key", "value", "variants", "bytes", "tags",
                 "value_charge", "status")

    def __init__(self, key: tuple, value, tags: tuple = (),
                 status: int = 200):
        self.key = key
        self.value = value
        self.tags = tags
        self.status = status
        # (kind, gzipped) -> (payload bytes, content type)
        self.variants: dict[tuple[str, bool], tuple[bytes, str]] = {}
        self.bytes = 0
        # the retained Python value's estimated footprint, charged to
        # the byte budget until the value is dropped (see
        # _VALUE_FOOTPRINT_FACTOR)
        self.value_charge = 0


class _Flight:
    __slots__ = ("key", "event", "entry", "done", "waiters")

    def __init__(self, key: tuple):
        self.key = key
        self.event = threading.Event()
        self.entry: CacheEntry | None = None
        self.done = False
        # completion callbacks for waiters that must not block a
        # thread on `event` — the async front end parks a coroutine
        # here and is woken via loop.call_soon_threadsafe
        self.waiters: list = []


# gzip threshold mirrors lambda_rt.http._send: small bodies are not
# worth the header overhead, and the cached variant must match what a
# cold response would have negotiated
_GZIP_MIN = 256
# recent per-tag invalidation sequences kept for store fencing; older
# evictions lower the floor and conservatively refuse stores instead
_TAG_SEQ_CAP = 65536
# the Python result object kept for lazy CSV rendering weighs several
# times its JSON bytes (per-row dataclasses + object headers): charge
# a conservative multiple to the byte budget while it is retained, so
# max-bytes bounds real memory, not just the wire bytes.  The value is
# dropped (and the charge released) once both plain variant kinds are
# rendered — gzip variants derive from the rendered bytes.
_VALUE_FOOTPRINT_FACTOR = 3


class ResultCache:
    """The router's exact result cache + single-flight coalescer.

    ``store_enabled`` and ``coalesce`` gate independently
    (``oryx.cluster.cache.enabled`` / ``oryx.cluster.coalesce.enabled``);
    either one brings the object into the router's context.
    """

    def __init__(self, config, metrics, registry, clock=None):
        c = "oryx.cluster"
        self.store_enabled = config.get_bool(f"{c}.cache.enabled")
        self.coalesce = config.get_bool(f"{c}.coalesce.enabled")
        self.max_entries = config.get_int(f"{c}.cache.max-entries")
        self.max_bytes = config.get_int(f"{c}.cache.max-bytes")
        self.coalesce_wait_sec = \
            config.get_int(f"{c}.coalesce.wait-ms") / 1000.0
        # hot-404 negative caching (roadmap item 2 leftover): unknown
        # user/item answers cached under the same epoch with the same
        # precise UP eviction — the fold-in that CREATES the id evicts
        # its 404
        self.negative_enabled = config.get_bool(
            f"{c}.cache.negative-enabled")
        self.quarantine_sec = config.get_int(
            f"{c}.cache.invalidation-quarantine-ms") / 1000.0
        if self.max_entries < 1 or self.max_bytes < 1:
            raise ValueError("oryx.cluster.cache budgets must be >= 1")
        self._metrics = metrics
        self._registry = registry
        self._clock = clock or _monotonic
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, CacheEntry] = OrderedDict()
        self._by_tag: dict[tuple, set] = {}
        self._bytes = 0
        # invalidation fencing: a global sequence, recent per-tag
        # (seq, wall) marks, and the floor below which fencing
        # information was dropped
        self._seq = 0
        self._tag_seq: OrderedDict[tuple, tuple[int, float]] = \
            OrderedDict()
        self._tag_floor = 0
        self._flush_seq = 0
        self._flights: dict[tuple, _Flight] = {}
        # operator stats (cumulative; /admin/cache)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.coalesced = 0
        self.coalesce_fallthroughs = 0
        self.stale_feed_stalls = 0
        self.store_rejects = 0
        self.epoch_flushes = 0
        self.negative_hits = 0
        self.negative_stores = 0

    @classmethod
    def from_config(cls, config, metrics, registry) -> "ResultCache | None":
        cache = cls(config, metrics, registry)
        return cache if (cache.store_enabled or cache.coalesce) else None

    # -- probe / lookup ------------------------------------------------------

    def probe(self, pattern: str, path: str, query: dict,
              params: dict) -> CacheProbe | None:
        """Mint this request's cache coordinates; None when the request
        is uncacheable (unknown route class, or per-request rescorer
        parameterization the router cannot prove is a pure function of
        model state).  ``params`` are the dispatcher's matched path
        variables."""
        if "rescorerParams" in query:
            return None
        tagged = route_tags(pattern, params)
        if tagged is None:
            return None
        users, items = tagged
        epoch = self._registry.generation_topology()
        if epoch[2]:
            # a replica group spans generations mid-rollout: a hedge
            # may fall back to an older-generation sibling and win, so
            # a complete 200 is not provably of the epoch the key
            # would claim — uncacheable until the group converges
            return None
        args = tuple(sorted((k, tuple(vs)) for k, vs in query.items()))
        key = (pattern, path, args, epoch)
        tags = tuple(("u", u) for u in users) \
            + tuple(("i", i) for i in items)
        with self._lock:
            seq = self._seq
        return CacheProbe(key, tags, epoch, seq, self._clock())

    def lookup(self, probe: CacheProbe) -> CacheEntry | None:
        if not self.store_enabled:
            return None
        with self._lock:
            entry = self._entries.get(probe.key)
            if entry is None:
                self.misses += 1
                self._metrics.inc("cache_misses")
                return None
            self._entries.move_to_end(probe.key)
            self.hits += 1
            self._metrics.inc("cache_hits")
            if entry.status != 200:
                self.negative_hits += 1
                self._metrics.inc("cache_negative_hits")
            return entry

    def lookup_present(self, probe: CacheProbe) -> CacheEntry | None:
        """Hit-or-nothing lookup for the async front end's on-loop
        fast path: a present entry counts (and serves) exactly like
        :meth:`lookup`; an ABSENT key is not counted as a miss — the
        bridged full dispatch re-probes the same request and counts
        its miss exactly once."""
        if not self.store_enabled:
            return None
        with self._lock:
            entry = self._entries.get(probe.key)
            if entry is None:
                return None
            self._entries.move_to_end(probe.key)
            self.hits += 1
            self._metrics.inc("cache_hits")
            if entry.status != 200:
                self.negative_hits += 1
                self._metrics.inc("cache_negative_hits")
            return entry

    # -- store ---------------------------------------------------------------

    def store(self, probe: CacheProbe, status: int, value, headers,
              render) -> CacheEntry | None:
        """Offer a finished handler result.  Returns the entry when the
        response was cacheable (the caller serves THROUGH it, so a hit
        is byte-identical to the miss that stored it), else None.

        Uncacheable: non-200s, any extra response header (the partial
        marker is the live case), bodiless (None) results.  Fenced —
        neither stored nor shared with coalesced followers: any tag
        invalidated after the probe or within the quarantine before
        it, an epoch flush, or an epoch that moved while the scatter
        was in flight."""
        if status != 200 or headers or value is None:
            return None
        if not (self.store_enabled or self.coalesce):
            return None
        if self._registry.generation_topology() != probe.epoch:
            return None  # generation/topology moved mid-request
        entry = CacheEntry(probe.key, value, probe.tags)
        # render the JSON-plain variant eagerly (outside the lock):
        # the leader responds through it, so the bytes exist anyway
        raw, _ = self._render_variant(entry, "json", False, render)
        entry.value_charge = _VALUE_FOOTPRINT_FACTOR * len(raw)
        entry.bytes += entry.value_charge
        with self._lock:
            if self._fenced_locked(probe):
                # an invalidation for this answer's tags arrived after
                # the probe (this scatter may have read pre-fold-in
                # state) or within the replica-catch-up quarantine
                # just before it: neither retained NOR shared — a
                # coalesced follower may have arrived after the tap
                # applied the eviction, and handing it these bytes
                # would serve pre-fold-in rows past the invalidation.
                # (The leader's own response legitimately raced the
                # fold-in; followers re-issue and read fresh state.)
                self.store_rejects += 1
                return None
            if not self.store_enabled:
                return entry  # coalesce-only: share, don't retain
            old = self._entries.pop(probe.key, None)
            if old is not None:
                self._bytes -= old.bytes
                self._unindex_locked(old)
            self._entries[probe.key] = entry
            self._bytes += entry.bytes
            for tag in probe.tags:
                self._by_tag.setdefault(tag, set()).add(probe.key)
            self._evict_over_budget_locked()
        return entry

    def store_negative(self, probe: CacheProbe, status: int,
                       message: str) -> CacheEntry | None:
        """Offer a 404 from the cacheable surface (unknown user/item).
        Same epoch key, same tag index, same fencing as :meth:`store`:
        the UP record of the fold-in that finally CREATES the id
        evicts its negative entry, so a hot missing id stops costing a
        full scatter without ever outliving its own absence.  Returns
        the entry for coalesced followers (a herd on a missing id
        collapses to one scatter too), or None when negative caching
        is off or the store is fenced."""
        if status != 404 or not self.negative_enabled:
            return None
        if not (self.store_enabled or self.coalesce):
            return None
        if self._registry.generation_topology() != probe.epoch:
            return None
        entry = CacheEntry(probe.key, message, probe.tags, status=status)
        # budget charge: the message plus per-entry bookkeeping — tiny
        # next to rendered bodies, but never free
        entry.bytes = len(message.encode("utf-8", "replace")) + 128
        with self._lock:
            if self._fenced_locked(probe):
                self.store_rejects += 1
                return None
            if not self.store_enabled:
                return entry  # coalesce-only: share, don't retain
            old = self._entries.pop(probe.key, None)
            if old is not None:
                self._bytes -= old.bytes
                self._unindex_locked(old)
            self._entries[probe.key] = entry
            self._bytes += entry.bytes
            for tag in probe.tags:
                self._by_tag.setdefault(tag, set()).add(probe.key)
            self.negative_stores += 1
            self._evict_over_budget_locked()
        return entry

    def _fenced_locked(self, probe: CacheProbe) -> bool:
        """Whether an epoch flush or a tag invalidation fences this
        probe's store: sequence fencing catches invalidations that
        arrived after the probe; the recency quarantine catches ones
        just before it (the tap can run a beat ahead of a replica's
        replay of the same topic — a scatter probed right after the
        eviction may still have read the pre-fold-in replica; past
        pathological replica lag the MODEL-publish epoch flush remains
        the backstop)."""
        if self._flush_seq > probe.seq or probe.seq < self._tag_floor:
            return True
        for tag in probe.tags:
            mark = self._tag_seq.get(tag)
            # quarantine measured against PROBE time, not store time:
            # the scatter began around the probe, so what matters is
            # whether the replicas had caught up by then — a scatter
            # slower than the quarantine must not age its way past
            # the fence
            if mark is not None and (
                    mark[0] > probe.seq
                    or probe.t - mark[1] < self.quarantine_sec):
                return True
        return False

    def _unindex_locked(self, entry: CacheEntry) -> None:
        # entries carry their tags, so unindexing is O(entry tags),
        # not a walk of the whole tag index
        for tag in entry.tags:
            keys = self._by_tag.get(tag)
            if keys is not None:
                keys.discard(entry.key)
                if not keys:
                    del self._by_tag[tag]

    def _evict_over_budget_locked(self) -> None:
        while self._entries and (len(self._entries) > self.max_entries
                                 or self._bytes > self.max_bytes):
            _, old = self._entries.popitem(last=False)
            self._bytes -= old.bytes
            self._unindex_locked(old)
            self.evictions += 1
            self._metrics.inc("cache_evictions")

    # -- rendering -----------------------------------------------------------

    def render(self, entry: CacheEntry, wants_csv: bool,
               gzip_ok: bool, render) -> tuple[bytes, str, bool]:
        """(payload, content type, gzipped) for one Accept/encoding
        combination, rendered once and memoized on the entry.
        ``render(value, kind)`` is the caller's canonical serializer
        (lambda_rt.http.json_or_csv under a canonical Accept), so a
        cached body is byte-identical to a cold one by construction."""
        kind = "csv" if wants_csv else "json"
        raw, ctype = self._render_variant(entry, kind, False, render)
        if not gzip_ok or len(raw) <= _GZIP_MIN:
            return raw, ctype, False
        gz, _ = self._render_variant(entry, kind, True, render)
        return gz, ctype, True

    def _render_variant(self, entry: CacheEntry, kind: str, gz: bool,
                        render) -> tuple[bytes, str]:
        got = entry.variants.get((kind, gz))
        if got is not None:
            return got
        if gz:
            raw, ctype = self._render_variant(entry, kind, False, render)
            # mtime pinned: the cached gzip bytes are deterministic, and
            # re-serving them skips the per-hit recompression entirely
            payload = gzip_mod.compress(raw, mtime=0)
        else:
            payload, ctype = render(entry.value, kind)
        with self._lock:
            got = entry.variants.get((kind, gz))
            if got is not None:
                return got
            entry.variants[(kind, gz)] = (payload, ctype)
            delta = len(payload)
            if not gz and entry.value is not None \
                    and ("json", False) in entry.variants \
                    and ("csv", False) in entry.variants:
                # both plain kinds rendered: the Python value has
                # nothing left to render (gzip derives from the
                # bytes) — drop it and release its footprint charge
                entry.value = None
                delta -= entry.value_charge
                entry.value_charge = 0
            entry.bytes += delta
            # identity, not key membership: the key may have been
            # re-stored by a newer entry while this (evicted) one was
            # still being served — charging ITS variant to the global
            # budget would leak phantom bytes that no eviction ever
            # reclaims
            if self._entries.get(entry.key) is entry:
                self._bytes += delta
                self._evict_over_budget_locked()
        return payload, ctype

    # -- invalidation feed ---------------------------------------------------

    def note_up(self, message: str) -> None:
        """One UP record from the router's update-topic tap: evict
        exactly the touched user's / item's keys.  The stale-feed chaos
        point models a stalled tap (records seen but not applied); the
        epoch flush on the next generation publish is the safety valve
        that bounds the resulting staleness."""
        if faults.fire("router-cache-stale-feed") == "drop":
            self.stale_feed_stalls += 1
            self._metrics.inc("cache_stale_feed_stalls")
            return
        try:
            up = json.loads(message)
            kind, id_ = str(up[0]), str(up[1])
            extras = up[3] if len(up) > 3 else None
        except (ValueError, IndexError, TypeError, KeyError):
            return  # malformed control traffic: the consumers count it
        tags = []
        if kind == "X":
            tags.append(("u", id_))
        elif kind == "Y":
            tags.append(("i", id_))
            # the item-side record of a fold-in names the user whose
            # interaction produced it: evict them too, so invalidation
            # does not depend on X/Y record ordering in the micro-batch
            if isinstance(extras, list):
                tags.extend(("u", str(u)) for u in extras)
        self._invalidate(tags)

    def note_generation_publish(self) -> None:
        """MODEL/MODEL-REF went by on the update topic: flush the
        epoch.  The generation is in every key, so stale entries could
        never be served — the flush reclaims their bytes and caps how
        long a stalled invalidation feed can matter."""
        self.flush("generation-publish")

    def _invalidate(self, tags) -> None:
        with self._lock:
            now = self._clock()
            for tag in tags:
                self._seq += 1
                self._tag_seq[tag] = (self._seq, now)
                self._tag_seq.move_to_end(tag)
                while len(self._tag_seq) > _TAG_SEQ_CAP:
                    _, dropped = self._tag_seq.popitem(last=False)
                    self._tag_floor = max(self._tag_floor, dropped[0])
                for key in self._by_tag.pop(tag, ()):
                    old = self._entries.pop(key, None)
                    if old is not None:
                        self._bytes -= old.bytes
                        self._unindex_locked(old)
                        self.invalidations += 1
                        self._metrics.inc("cache_invalidations")

    def flush(self, reason: str) -> int:
        """Drop every entry (generation publish, topology cutover, or
        the /admin/cache operator hatch).  Returns entries dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_tag.clear()
            self._bytes = 0
            self._seq += 1
            self._flush_seq = self._seq
            self.epoch_flushes += 1
        return n

    # -- single-flight coalescing --------------------------------------------

    def begin_flight(self, probe: CacheProbe,
                     deadline) -> tuple[str, object]:
        """("lead", flight) — this request computes and MUST call
        :meth:`finish_flight`; ("coalesced", entry) — a leader finished
        with a shareable result; ("solo", None) — coalescing is off, or
        the leader died / timed out and this request falls through to
        its own scatter."""
        if not self.coalesce:
            return "solo", None
        with self._lock:
            fl = self._flights.get(probe.key)
            if fl is None:
                fl = _Flight(probe.key)
                self._flights[probe.key] = fl
                lead = True
            else:
                lead = False
        if lead:
            try:
                # chaos: the coalescing leader dies before completing
                # its scatter — followers must re-issue, never hang
                faults.fire("router-coalesce-leader-death")
            except BaseException:
                self.finish_flight(fl, None)
                raise
            return "lead", fl
        timeout = self.coalesce_wait_sec
        if deadline is not None:
            timeout = min(timeout, max(0.0, deadline.remaining()))
        clockmod.wait(fl.event, timeout)
        if fl.done and fl.entry is not None:
            with self._lock:
                self.coalesced += 1
            self._metrics.inc("coalesced_requests")
            return "coalesced", fl.entry
        with self._lock:
            self.coalesce_fallthroughs += 1
        return "solo", None

    def flight_for(self, key: tuple) -> "_Flight | None":
        """The in-flight leader for a key, if any — the async front
        end joins it on-loop instead of parking a thread."""
        if not self.coalesce:
            return None
        with self._lock:
            return self._flights.get(key)

    def add_flight_waiter(self, flight: _Flight, callback) -> bool:
        """Register a completion callback on an in-flight leader.
        Returns False when the flight already finished (the caller
        reads ``flight.entry`` directly instead of waiting).  The
        callback runs on the LEADER's thread at finish time and must
        be cheap and non-raising (the async front end passes
        ``loop.call_soon_threadsafe``)."""
        with self._lock:
            if flight.done:
                return False
            flight.waiters.append(callback)
            return True

    def count_coalesced(self) -> None:
        """Count one follower served from a leader's flight — the
        async front end's on-loop join path (begin_flight counts the
        thread-parked form itself)."""
        with self._lock:
            self.coalesced += 1
        self._metrics.inc("coalesced_requests")

    def finish_flight(self, flight: _Flight,
                      entry: CacheEntry | None) -> None:
        """Publish the leader's outcome (idempotent; entry None =
        uncacheable result or leader death — followers re-issue)."""
        with self._lock:
            if flight.done:
                return
            flight.done = True
            flight.entry = entry
            waiters, flight.waiters = flight.waiters, []
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
        flight.event.set()
        for cb in waiters:
            try:
                cb()
            except Exception:  # noqa: BLE001 — waiters are best-effort
                pass

    # -- operator surface ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.store_enabled,
                "coalesce": self.coalesce,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / (self.hits + self.misses), 4)
                if (self.hits + self.misses) else None,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "coalesced_requests": self.coalesced,
                "coalesce_fallthroughs": self.coalesce_fallthroughs,
                "stale_feed_stalls": self.stale_feed_stalls,
                "store_rejects": self.store_rejects,
                "epoch_flushes": self.epoch_flushes,
                "negative_enabled": self.negative_enabled,
                "negative_stores": self.negative_stores,
                "negative_hits": self.negative_hits,
                "in_flight": len(self._flights),
            }


class ShardResultCache:
    """Replica-side exact result cache for the ``/shard/*`` surface
    (``oryx.cluster.replica-cache.*``, off by default).

    The router's result cache saves the scatter; this one saves the
    DEVICE: a cold-router miss on a shard query the replica already
    answered (a restarted router, a second router in the same region,
    a cache-busted public request that maps to the same internal
    query) skips scoring entirely.  Same epoch discipline as the
    router cache, one level stricter: the epoch is a counter bumped on
    EVERY model-state record this replica applies (UP fold-ins and
    MODEL/MODEL-REF publishes alike — the serving layer's update tap
    feeds :meth:`note_record`), so an entry serves only while nothing
    whatsoever has changed in the model it was computed from.  Exact
    by construction, no per-tag index needed.

    The bump happens when the record is HANDED to the model manager,
    a beat before the apply completes; like the router cache's
    invalidation quarantine, stores are refused for a configured
    window after the last bump so an answer computed from mid-apply
    state can never be retained under the post-apply epoch.

    Entries hold the COMPLETE rendered answer (status + response
    headers + body bytes) keyed by ``(method, path, body)``: a hit
    replays the exact bytes the frame dispatcher produced for the
    first asker, byte-identical by construction.  Bounded LRU with a
    byte budget, same shape as the router cache's.
    """

    def __init__(self, config, metrics=None, clock=None):
        c = "oryx.cluster.replica-cache"
        self.enabled = config.get_bool(f"{c}.enabled")
        self.max_entries = config.get_int(f"{c}.max-entries")
        self.max_bytes = config.get_int(f"{c}.max-bytes")
        self.quarantine_sec = \
            config.get_int(f"{c}.quarantine-ms") / 1000.0
        if self.max_entries < 1 or self.max_bytes < 1:
            raise ValueError(
                "oryx.cluster.replica-cache budgets must be >= 1")
        self._metrics = metrics
        self._clock = clock or _monotonic
        self._lock = threading.Lock()
        # (method, path, body) -> (epoch, status, headers, body, bytes)
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0
        self._epoch = 0
        self._last_bump = -1e9
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.store_rejects = 0

    @classmethod
    def from_config(cls, config, metrics=None) -> "ShardResultCache | None":
        cache = cls(config, metrics)
        return cache if cache.enabled else None

    # -- epoch feed ----------------------------------------------------------

    def note_record(self) -> None:
        """One model-state record (UP / MODEL / MODEL-REF) is about to
        be applied: move the epoch.  Every cached entry is keyed under
        the previous epoch and stops serving instantly; their bytes
        are reclaimed lazily as lookups touch them and by LRU
        pressure."""
        with self._lock:
            self._epoch += 1
            self._last_bump = self._clock()

    def tap(self, stream):
        """Wrap the serving layer's (heartbeat-filtered) update replay:
        the epoch moves on BOTH sides of every record's apply.  The
        pre-yield bump fences new lookups off entries computed from
        the pre-apply model; the post-yield bump (which runs when the
        consumer asks for the NEXT record — i.e. the moment this
        record's apply completed) retires anything a mid-apply request
        managed to store under the in-between epoch.  Together they
        make the stale-store window zero REGARDLESS of how long the
        apply takes (a sliced MODEL-REF load can run for seconds —
        far past any fixed quarantine); the quarantine remains as
        defense in depth for clock-adjacent races."""
        for km in stream:
            self.note_record()
            yield km
            self.note_record()

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- lookup / store ------------------------------------------------------

    def lookup(self, method: str, path: str, body: bytes
               ) -> "tuple[int, dict, bytes] | None":
        """(status, response headers, body bytes) when the exact query
        was answered under the CURRENT epoch; None (counted as a miss)
        otherwise.  A stale-epoch entry is dropped on touch."""
        key = (method, path, body)
        with self._lock:
            got = self._entries.get(key)
            if got is not None and got[0] == self._epoch:
                self._entries.move_to_end(key)
                self.hits += 1
                if self._metrics is not None:
                    self._metrics.inc("shard_cache_hits")
                return got[1], got[2], got[3]
            if got is not None:
                # keyed under a retired epoch: unservable, reclaim now
                del self._entries[key]
                self._bytes -= got[4]
            self.misses += 1
            if self._metrics is not None:
                self._metrics.inc("shard_cache_misses")
            return None

    def store(self, method: str, path: str, body: bytes,
              epoch0: int, status: int, headers: dict,
              payload: bytes) -> None:
        """Offer a finished answer computed while the epoch was
        ``epoch0``.  Refused for non-200s, when the epoch moved during
        the request, or within the quarantine window after the last
        bump (the answer may have read mid-apply state)."""
        if not self.enabled or status != 200:
            return
        size = len(payload) + len(path) + len(body) + 160
        with self._lock:
            if self._epoch != epoch0 \
                    or self._clock() - self._last_bump \
                    < self.quarantine_sec:
                self.store_rejects += 1
                return
            key = (method, path, body)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[4]
            self._entries[key] = (epoch0, status, dict(headers),
                                  payload, size)
            self._bytes += size
            while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped[4]
                self.evictions += 1

    def flush(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "epoch": self._epoch,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "store_rejects": self.store_rejects,
            }
